"""Fleet-wide observability plane (ISSUE 19, docs/OBSERVABILITY.md
"Fleet observability").

Unit layer: the tracer's delta-drain cursor, remote-span ingest (id
offsetting, ``remote_parent_id`` re-parenting, clock rebase), the
FleetJournal's exactly-once / schema-refusal / bounded-ring books, the
merged fleet Chrome trace's process→pid / replica→tid mapping, the
flight recorder's role+pid dump stamping and dead-owner sweep, and the
ObsEndpoint HTTP routes over a live frontend.

Integration layer (in-thread replica servers over real TCP, the
test_fabric idiom): a traced fabric fleet must yield ONE stitched
cross-process ``req-<uid>`` chain in the frontend tracer, journal
events from every server source exactly once, and — the counter-reset
satellite — forwarded engine counters that stay monotonic through a
supervisor replica swap (server engine reset) with no negative
windowed deltas.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.serving import ServingConfig, ServingFrontend
from deepspeed_tpu.telemetry import validate_chrome_trace
from deepspeed_tpu.telemetry.fleet import (FleetJournal, ObsEndpoint,
                                           fleet_chrome_trace,
                                           ingest_remote_spans,
                                           source_id_offset)
from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder
from deepspeed_tpu.telemetry.journal import OpsJournal
from deepspeed_tpu.telemetry.tracer import Tracer

from test_fabric import (VOCAB, _Servers, fabric_cfg, prompts, run_fleet,
                         tiny_engine)


def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ========================================================== span forwarding
class TestSpanForwarding:
    def test_drain_completed_cursor(self):
        tr = Tracer()
        for i in range(3):
            tr.begin(f"s{i}").end()
        spans, cur = tr.drain_completed(0, limit=2)
        assert [s["name"] for s in spans] == ["s0", "s1"]
        spans, cur = tr.drain_completed(cur, limit=2)
        assert [s["name"] for s in spans] == ["s2"]
        assert tr.drain_completed(cur)[0] == []
        # the hello idiom: a cursor seeded from completed_total skips
        # history entirely
        tr.begin("s3").end()
        assert tr.drain_completed(tr.completed_total)[0] == []

    def test_ingest_offsets_reparents_and_rebases(self):
        tr = Tracer()
        local_parent = 7            # a frontend-local rpc span id
        remote = [
            {"name": "server", "trace_id": "req-1", "span_id": 1,
             "parent_id": None, "t_start": 10.0, "t_end": 10.5,
             "attrs": {"remote_parent_id": local_parent, "replica": 3}},
            {"name": "prefill", "trace_id": "req-1", "span_id": 2,
             "parent_id": 1, "t_start": 10.1, "t_end": 10.2, "attrs": {}},
        ]
        off = source_id_offset(3)
        n = ingest_remote_spans(tr, remote, offset=off, clock_offset_s=0.5,
                                source="replica-3@h", pid=4242)
        assert n == 2
        by_name = {s["name"]: s for s in tr.export()}
        srv, pre = by_name["server"], by_name["prefill"]
        assert srv["span_id"] == 1 + off
        # the cross-process edge: remote_parent_id used VERBATIM
        assert srv["parent_id"] == local_parent
        # remote-local parents shift with their span
        assert pre["parent_id"] == 1 + off
        assert srv["t_start"] == pytest.approx(9.5)
        assert srv["t_end"] == pytest.approx(10.0)
        for s in (srv, pre):
            assert s["attrs"]["source"] == "replica-3@h"
            assert s["attrs"]["pid"] == 4242

    def test_source_offsets_disjoint(self):
        a, b = source_id_offset(0), source_id_offset(1)
        assert a > 0 and b - a >= 2 ** 32


# ============================================================ fleet journal
class TestFleetJournal:
    def _remote_events(self, n=3, source="replica-9@h"):
        j = OpsJournal(source=source)
        for i in range(n):
            j.emit("server_hello", replica=9, role="mixed", reset=bool(i))
        return source, j.events()

    def test_exactly_once_across_replay(self):
        fj = FleetJournal(OpsJournal(source="serving"))
        src, evs = self._remote_events(3)
        assert fj.ingest(src, evs) == (3, 0)
        # reconnect replays the server's ring: all duplicates, none
        # re-ingested, none counted as dropped
        assert fj.ingest(src, evs) == (0, 0)
        book = fj.sources()[src]
        assert book["events"] == 3 and book["last_seq"] == 3
        assert book["duplicates"] == 3 and book["dropped"] == 0
        assert book["remote"] == 1

    def test_schema_invalid_refused_and_counted(self):
        fj = FleetJournal(OpsJournal(source="serving"))
        bad = [{"seq": 1, "t": 0.0, "wall_time": 0.0, "source": "x",
                "kind": "no_such_kind", "detail": {}},
               "not an object"]
        accepted, dropped = fj.ingest("x", bad)
        assert (accepted, dropped) == (0, 2)
        assert fj.sources()["x"]["dropped"] == 2

    def test_merged_view_and_count(self):
        local = OpsJournal(source="serving")
        fj = FleetJournal(local)
        local.emit("obs_listen", address="127.0.0.1:1")
        src, evs = self._remote_events(2)
        fj.ingest(src, evs)
        merged = fj.events()
        assert len(merged) == 3
        assert merged == sorted(merged, key=lambda e: e["wall_time"])
        assert fj.count("server_hello") == 2
        assert fj.count("obs_listen") == 1
        assert fj.events(sources=[src]) == [e for e in merged
                                            if e["source"] == src]

    def test_ring_bounded_per_source(self):
        fj = FleetJournal(OpsJournal(source="serving"),
                          capacity_per_source=4)
        src, evs = self._remote_events(10)
        assert fj.ingest(src, evs) == (10, 0)
        book = fj.sources()[src]
        assert book["events"] == 4 and book["last_seq"] == 10


# ============================================================= chrome trace
class TestFleetChromeTrace:
    def test_pid_tid_mapping_and_validity(self):
        spans = [
            {"name": "queue", "trace_id": "req-1", "span_id": 1,
             "parent_id": None, "t_start": 1.0, "t_end": 1.1, "attrs": {}},
            {"name": "server", "trace_id": "req-1", "span_id": 2,
             "parent_id": 1, "t_start": 1.02, "t_end": 1.08,
             "attrs": {"source": "replica-0@h", "replica": 0}},
            {"name": "server", "trace_id": "req-2", "span_id": 3,
             "parent_id": None, "t_start": 1.2, "t_end": 1.3,
             "attrs": {"source": "replica-1@h", "replica": 1}},
        ]
        trace = fleet_chrome_trace(spans, meta={"phase": "test"})
        assert validate_chrome_trace(trace) == []
        metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        pnames = {e["args"]["name"]: e["pid"] for e in metas
                  if e["name"] == "process_name"}
        assert set(pnames) == {"frontend", "replica-0@h", "replica-1@h"}
        assert pnames["frontend"] == 1
        assert len(set(pnames.values())) == 3
        xs = {e["args"]["trace_id"]: e["pid"]
              for e in trace["traceEvents"]
              if e["ph"] == "X" and e["name"] == "server"}
        assert xs["req-1"] == pnames["replica-0@h"]
        assert xs["req-2"] == pnames["replica-1@h"]


# =========================================================== flight recorder
class TestFlightRecorderFleet:
    def test_dump_tag_carries_role_and_pid(self, tmp_path):
        rec = FlightRecorder(Tracer(), dump_dir=str(tmp_path),
                             role="replica-3")
        paths = rec.dump(reason="on_demand")
        for p in paths.values():
            assert os.path.basename(p).endswith(
                f"on_demand_replica-3_{os.getpid()}.json")

    def test_stale_dead_owner_sweep(self, tmp_path):
        proc = subprocess.Popen([sys.executable, "-c", ""])
        proc.wait()
        dead, live = proc.pid, os.getpid()
        (tmp_path / f"flightrec_001_error_replica-0_{dead}.json").write_text(
            "{}")
        (tmp_path / f"trace_001_error_replica-0_{dead}.json").write_text(
            "{}")
        (tmp_path / f"flightrec_001_error_frontend_{live}.json").write_text(
            "{}")
        (tmp_path / "flightrec_unparseable.json").write_text("{}")
        (tmp_path / "unrelated.json").write_text("{}")
        FlightRecorder(Tracer(), dump_dir=str(tmp_path))
        left = sorted(p.name for p in tmp_path.iterdir())
        assert left == ["flightrec_001_error_frontend_%d.json" % live,
                        "flightrec_unparseable.json", "unrelated.json"]


# ============================================================== obs endpoint
class TestObsEndpoint:
    def _get(self, addr, path):
        with urllib.request.urlopen(f"http://{addr}{path}",
                                    timeout=30) as resp:
            return resp.status, resp.read()

    def test_routes_over_live_frontend(self):
        fe = ServingFrontend([tiny_engine()], ServingConfig(
            max_queue_depth=64,
            telemetry={"enabled": True},
            observability={"enabled": True, "listen": "127.0.0.1:0"}))
        try:
            addr = fe.observability_address
            assert addr and addr.rsplit(":", 1)[1] != "0"
            run_fleet(fe, prompts(2, 5), 4)
            status, body = self._get(addr, "/metrics")
            assert status == 200
            assert b"obs_requests" in body and b"requests_completed" in body
            status, body = self._get(addr, "/health")
            health = json.loads(body)
            assert status == 200 and "replicas" in health
            assert health["observability_address"] == addr
            assert "fleet_journal" in health
            status, body = self._get(addr, "/trace")
            trace = json.loads(body)
            assert status == 200
            assert validate_chrome_trace(trace) == []
            assert any(e.get("name") == "decode_step" or e.get("ph")
                       for e in trace["traceEvents"])
            with pytest.raises(urllib.error.HTTPError):
                self._get(addr, "/no_such_route")
            assert fe.metrics_snapshot()["obs_requests"] >= 3
            assert fe.journal.count("obs_listen") == 1
        finally:
            fe.shutdown(drain=False, timeout=5)
        # shutdown closed the listener
        with pytest.raises(OSError):
            self._get(addr, "/metrics")

    def test_disabled_is_absent(self):
        fe = ServingFrontend([tiny_engine()],
                             ServingConfig(max_queue_depth=64))
        try:
            assert fe.observability_address is None
            assert fe._obs_endpoint is None
        finally:
            fe.shutdown(drain=False, timeout=5)


# ========================================================== fabric end-to-end
class TestFabricTracePropagation:
    def test_cross_process_chain_stitches(self):
        with _Servers(2) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, telemetry={"enabled": True}))
            try:
                ps = prompts(4, 11)
                run_fleet(fe, ps, 4)
                # span/journal deltas ride the ~1s status stream
                assert _wait(lambda: fe.metrics_snapshot()
                             ["spans_forwarded"] > 0), \
                    "no spans ever forwarded on the status stream"
                assert _wait(lambda: sum(
                    v.get("remote", 0)
                    for v in fe.fleet.sources().values()) >= 2), \
                    "journal never heard from both servers"
                spans = fe.tracer.export()
                servers = [s for s in spans if s["name"] == "server"]
                assert servers, "no server-side spans in the merged set"
                ids = {s["span_id"] for s in spans}
                for s in servers:
                    assert str(s["trace_id"]).startswith("req-")
                    assert s["parent_id"] in ids, \
                        "cross-process edge failed to stitch"
                    assert "replica-" in s["attrs"]["source"]
                # every remote source's books balance: exactly-once
                books = fe.fleet.sources()
                remote = {k: v for k, v in books.items() if v["remote"]}
                assert len(remote) == 2
                for book in remote.values():
                    assert book["events"] == book["last_seq"]
                    assert book["dropped"] == 0
                report = fe.health_report()
                assert len(report["remotes"]) == 2
                for r in report["remotes"]:
                    assert r["connected"]
                    assert isinstance(r["clock_offset_s"], float)
                assert validate_chrome_trace(
                    fleet_chrome_trace(spans)) == []
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_counter_reset_detection_across_replica_swap(self):
        """Satellite: forwarded engine counters must stay monotonic
        through a supervisor replica swap (transport loss -> re-dial ->
        server-side engine reset restarts the server's cumulative
        counters from zero) — the frontend's cumulative view never goes
        backwards and the windowed deltas spanning the swap never go
        negative."""
        from deepspeed_tpu.serving.fabric.remote import RemoteHandle

        rng = np.random.default_rng(3)
        head = rng.integers(0, VOCAB, size=16).tolist()
        mk = lambda seed: [head + rng.integers(0, VOCAB, size=6).tolist()
                           for _ in range(3)]
        # the SERVER owns its engine's config: prefix caching must be
        # enabled there for hits to exist server-side at all
        srv_cfg = ServingConfig(prefix_cache={"enabled": True})
        with _Servers(1, server_config=srv_cfg, heartbeat_s=0.2) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, heartbeat_s=0.2,
                fault_tolerance={"enabled": True, "max_retries": 3,
                                 "restart_backoff_s": 0.05,
                                 "max_restarts_in_window": 10}))
            watched = RemoteHandle._FORWARDED_COUNTERS
            try:
                run_fleet(fe, mk(1), 4)   # first wave primes the cache
                run_fleet(fe, mk(1), 4)   # second wave hits the head
                assert _wait(lambda: fe.metrics_snapshot()
                             ["prefix_blocks_hit"] > 0), \
                    "shared-prefix traffic never forwarded a hit counter"
                fe.windowed.tick()
                before = fe.metrics_snapshot()
                # sever the transport: the supervisor re-dials with a
                # server-side reset — a FRESH engine whose cumulative
                # counters restart from zero
                handle = next(r for r in fe.router.replicas
                              if isinstance(r, RemoteHandle))
                handle._conn.close("injected transport loss")
                assert _wait(lambda: fe.journal.count(
                    "replica_reconnected") > 0), "supervisor never re-dialed"
                run_fleet(fe, mk(2), 4)
                assert _wait(lambda: fe.metrics_snapshot()
                             ["prefix_blocks_hit"]
                             > before["prefix_blocks_hit"]), \
                    "post-swap traffic never moved the forwarded counter"
                fe.windowed.tick()
                after = fe.metrics_snapshot()
                for name in watched:
                    assert after.get(name, 0.0) >= before.get(name, 0.0), \
                        f"{name} went backwards across the replica swap"
                    delta = fe.windowed.window_delta(name, 3600.0)
                    assert delta >= 0.0, \
                        f"{name} produced a negative windowed delta"
                # the reset-detection branch itself: a status frame whose
                # counters are BELOW the high-water mark (server engine
                # reset) re-bases instead of subtracting into a phantom
                new_handle = next(r for r in fe.router.replicas
                                  if isinstance(r, RemoteHandle))
                base = fe.metrics_snapshot()["prefix_blocks_hit"]
                new_handle._counters_last["prefix_blocks_hit"] = 10 ** 9
                new_handle._ev_status(
                    {"counters": {"prefix_blocks_hit": 2.0}})
                got = fe.metrics_snapshot()["prefix_blocks_hit"]
                assert got == pytest.approx(base + 2.0), \
                    "reset epoch was not re-based from zero"
            finally:
                fe.shutdown(drain=False, timeout=5)
