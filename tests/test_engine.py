"""End-to-end engine tests on the 8-device virtual mesh.

Counterpart of reference tests/unit/runtime/test_ds_initialize.py and
zero/test_zero.py: initialize → train loop → loss decreases, for each ZeRO
stage, plus checkpoint round-trip (tests/unit/checkpoint/).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.transformer import TINY_TEST


def tiny_data(n=64, seq=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(n, seq + 1), dtype=np.int64)}


def make_config(stage=0, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_max_lr": 1e-3, "warmup_num_steps": 5}},
        "zero_optimization": {"stage": stage},
        "mesh": {"data": -1, "fsdp": 2},
        "gradient_clipping": 1.0,
        "steps_per_print": 100,
    }
    cfg.update(over)
    return cfg


def run_steps(engine, data, steps=4):
    loader = deepspeed_tpu.runtime.dataloader.RepeatingLoader(
        engine.deepspeed_io(data))
    it = iter(loader)
    losses = []
    for _ in range(steps):
        for _ in range(engine.gradient_accumulation_steps()):
            loss = engine(next(it))
            engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_train(stage):
    model = build_model("tiny")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=make_config(stage))
    losses = run_steps(engine, tiny_data(), steps=6)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    assert engine.global_steps == 6


def test_train_batch_api():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config(1),
        training_data=tiny_data())
    loader = deepspeed_tpu.runtime.dataloader.RepeatingLoader(
        engine.training_dataloader)
    it = iter(loader)
    l0 = float(engine.train_batch(it))
    for _ in range(5):
        l1 = float(engine.train_batch(it))
    assert l1 < l0


def test_eval_batch():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config(2))
    batch = {"input_ids": tiny_data(8)["input_ids"]}
    loss = float(engine.eval_batch(batch))
    assert np.isfinite(loss)


def test_batch_size_resolution():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config=make_config(0, train_micro_batch_size_per_gpu=2,
                           gradient_accumulation_steps=4))
    # dp world = 8 (data=4 × fsdp=2)
    assert engine.train_batch_size() == 2 * 4 * 8


def test_fp16_dynamic_loss_scale():
    cfg = make_config(1)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=cfg)
    assert engine.loss_scale == 2.0 ** 8
    losses = run_steps(engine, tiny_data(), steps=3)
    assert np.isfinite(losses).all()


def test_bf16():
    cfg = make_config(2)
    cfg["bf16"] = {"enabled": True}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=cfg)
    losses = run_steps(engine, tiny_data(), steps=4)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_checkpoint_roundtrip(tmp_path):
    data = tiny_data()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config(2))
    run_steps(engine, data, steps=3)
    tag_dir = engine.save_checkpoint(str(tmp_path), client_state={"note": "hi"})
    assert tag_dir

    # fresh engine, different mesh split → universal layout must still load
    import deepspeed_tpu.parallel.topology as topo

    topo.reset_topology()
    cfg = make_config(3)
    cfg["mesh"] = {"data": -1, "fsdp": 4}
    engine2, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)
    path, client = engine2.load_checkpoint(str(tmp_path))
    assert client == {"note": "hi"}
    assert engine2.global_steps == engine.global_steps

    # params equal
    import jax

    p1 = jax.tree.leaves(engine.state.params)
    p2 = jax.tree.leaves(engine2.state.params)
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_save_16bit_model(tmp_path):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config(0))
    path = engine.save_16bit_model(str(tmp_path))
    loaded = np.load(path)
    assert "embed.wte" in loaded.files


def test_zero3_param_sharding():
    """ZeRO-3: large params must actually be sharded over the fsdp axis."""
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config(3))
    wte = engine.state.params["embed"]["wte"]
    from deepspeed_tpu.parallel.topology import FSDP_AXIS

    assert FSDP_AXIS in str(wte.sharding.spec), wte.sharding


def test_check_numerics_names_poisoned_leaves(devices8):
    """The numeric sanitizer (reference runtime/utils.py CheckOverflow /
    loss_scaler._has_inf_or_nan) must fail loudly with the offending leaf
    paths instead of letting NaNs propagate."""
    import jax

    import deepspeed_tpu.parallel.topology as topo

    topo.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
                "check_numerics": True,
                "mesh": {"data": -1, "fsdp": 1},
                "steps_per_print": 10**9})
    rng = np.random.default_rng(0)
    dp = engine.topology.get_data_parallel_world_size()
    data = {"input_ids": rng.integers(0, 256, size=(2 * dp, 33),
                                      dtype=np.int64)}
    # clean step passes
    loss = engine(dict(data))
    engine.backward(loss)
    engine.step()

    # poison one param leaf -> the next micro step must raise and name it.
    # The jitted step donates its input buffers, so snapshot with copies.
    import jax.numpy as jnp

    clean = jax.tree_util.tree_map(jnp.copy, engine.state.params)
    poisoned = jax.tree_util.tree_map(jnp.copy, clean)
    poisoned["final_norm"]["w"] = poisoned["final_norm"]["w"] * jnp.nan
    engine.state = engine.state._replace(params=poisoned)
    with pytest.raises(FloatingPointError) as e:
        engine(dict(data))
    assert "final_norm" in str(e.value)

    # step-path: poisoned accumulated grads must be named too (the scan
    # runs BEFORE the update zeroes grad_acc / skips the param write).
    # Restore grad_acc too — the poisoned forward above NaN'd every leaf,
    # which would make the leaf-isolation assertion vacuous.
    engine.state = engine.state._replace(
        params=clean,
        grad_acc=jax.tree_util.tree_map(jnp.zeros_like,
                                        engine.state.grad_acc))
    loss = engine(dict(data))
    engine.backward(loss)
    acc = jax.tree_util.tree_map(jnp.copy, engine.state.grad_acc)
    acc["embed"]["wte"] = acc["embed"]["wte"] * jnp.nan
    engine.state = engine.state._replace(grad_acc=acc)
    with pytest.raises(FloatingPointError) as e:
        engine.step()
    assert "grad_acc" in str(e.value) and "wte" in str(e.value)
    topo.reset_topology()
