"""CI hygiene tripwires (ISSUE 2 satellites).

1. ``shard_map`` must be imported from ``deepspeed_tpu.compat`` everywhere
   — the installed JAX may only provide it under ``jax.experimental`` (and
   with a differently-spelled replication-check kwarg), so a direct
   ``from jax import shard_map`` / ``jax.shard_map(...)`` regresses the
   ~80 SPMD tests the shim un-gated.
2. The ``slow`` marker the tier-1 budget depends on (``-m 'not slow'``)
   must stay registered in pyproject.toml.
"""

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent
DIRECT_IMPORT = re.compile(
    r"^\s*(?:from\s+jax(?:\.experimental(?:\.shard_map)?)?\s+import\s+"
    r"(?:[\w,\s]*\bshard_map\b)|.*\bjax\.shard_map\s*\()", re.M)


def _py_sources():
    for root in ("deepspeed_tpu", "tests"):
        for path in sorted((REPO / root).rglob("*.py")):
            if path.name in ("compat.py", "test_marker_audit.py"):
                continue        # the shim itself, and this file's docstring
            yield path


def test_no_direct_shard_map_imports():
    offenders = []
    for path in _py_sources():
        for m in DIRECT_IMPORT.finditer(path.read_text()):
            line = m.group(0).strip()
            if line.startswith("#"):
                continue
            offenders.append(f"{path.relative_to(REPO)}: {line}")
    assert not offenders, (
        "import shard_map from deepspeed_tpu.compat, not jax directly "
        "(see deepspeed_tpu/compat.py):\n" + "\n".join(offenders))


def test_slow_marker_registered():
    pyproject = (REPO / "pyproject.toml").read_text()
    markers = re.search(r"markers\s*=\s*\[(.*?)\]", pyproject, re.S)
    assert markers and "slow" in markers.group(1), (
        "the 'slow' pytest marker must stay registered in pyproject.toml "
        "(the tier-1 suite runs -m 'not slow')")
