"""Activation checkpointing API tests (reference
tests/unit/runtime/activation_checkpointing/test_activation_checkpointing.py):
remat correctness — same values and gradients as the unremat function —
plus dropout determinism under recompute and the configure surface."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime import activation_checkpointing as ac


def test_checkpoint_matches_plain():
    w = jnp.asarray(np.random.default_rng(0).normal(size=(16, 16))
                    .astype(np.float32))
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 16))
                    .astype(np.float32))

    def f(w, x):
        h = jnp.tanh(x @ w)
        return jnp.sum(h @ w.T)

    def f_ckpt(w, x):
        return ac.checkpoint(f, w, x)

    np.testing.assert_allclose(np.asarray(f(w, x)),
                               np.asarray(f_ckpt(w, x)), rtol=1e-6)
    g_plain = jax.grad(f)(w, x)
    g_ckpt = jax.grad(f_ckpt)(w, x)
    np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_ckpt),
                               rtol=1e-6)


def test_checkpoint_policy_and_dropout_determinism():
    key = jax.random.PRNGKey(0)
    w = jnp.ones((8, 8))

    def f(w, key):
        h = w @ w
        mask = jax.random.bernoulli(key, 0.5, h.shape)
        return jnp.sum(h * mask)

    for policy in (None, "dots_saveable", "nothing_saveable"):
        out = ac.checkpoint(f, w, key, policy=policy)
        grad = jax.grad(lambda w: ac.checkpoint(f, w, key, policy=policy))(w)
        # recompute replays the same PRNG key: value and grad agree with
        # the unremat version (the CudaRNGStatesTracker role)
        np.testing.assert_allclose(np.asarray(out), np.asarray(f(w, key)),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grad),
                                   np.asarray(jax.grad(f)(w, key)),
                                   rtol=1e-6)


def test_configure_and_probes():
    ac.configure(partition_activations=True, num_checkpoints=4)
    assert ac.is_configured()
    assert ac.CheckpointFunction.apply(lambda x: x * 2, jnp.ones(3))[0] == 2
    assert ac.get_rng_tracker() is None
    ac.model_parallel_cuda_manual_seed(1234)   # no-op by design
