"""Elasticity tests (reference tests/unit/elasticity/test_elastic.py):
compatible batch/chip-count algebra (v0.1/v0.2), engine adoption of the
elastic batch config, and a restart-based scale-down resume — checkpoint on
8 chips, resume on a 4-chip mesh with the same global batch."""

import itertools

import jax
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.parallel.topology as topo
from deepspeed_tpu.elasticity import (ElasticityConfigError,
                                      ElasticityIncompatibleWorldSize,
                                      compute_elastic_config,
                                      get_compatible_chips_v01,
                                      get_compatible_chips_v02,
                                      validate_elastic_config)
from deepspeed_tpu.models import build_model


def test_v01_picks_most_compatible_batch():
    batch, valid = get_compatible_chips_v01([2, 4, 6], 2000)
    # lcm=12 scaled by the largest HCN fitting 2000 → 1680 = 840 slots at
    # micro 2: the divisor-richest candidate.
    assert batch == 1680
    assert 8 in valid and 7 in valid        # 840 % 7 == 0
    assert 11 not in valid
    for chips in valid:
        assert any(batch % (m * chips) == 0 for m in (2, 4, 6))


def test_v01_prefer_smaller():
    b_large, _ = get_compatible_chips_v01([2, 4], 100, prefer_larger=True)
    b_small, _ = get_compatible_chips_v01([2, 4], 100, prefer_larger=False)
    assert b_small <= b_large


def test_v01_micro_exceeds_max_raises():
    with pytest.raises(ElasticityConfigError):
        get_compatible_chips_v01([64], 32)


def test_v02_model_parallel_host_granularity():
    batch, valid_dp, micro = get_compatible_chips_v02(
        [2, 4], 1000, current_num_chips=8, chips_per_host=4,
        model_parallel_size=2)
    # dp = chips/mp = 4, dp_per_host = 2: valid dp worlds are host multiples
    assert all(v % 2 == 0 for v in valid_dp)
    assert micro in (2, 4)
    assert batch % (micro * 4) == 0         # reachable on the current dp=4


def test_v02_incompatible_world_falls_back_to_current():
    batch, valid, micro = get_compatible_chips_v02(
        [5], 100, current_num_chips=7, chips_per_host=1)
    assert valid == [7]
    assert batch == 5 * 7 * (100 // 35)
    assert micro == 5


def test_compute_elastic_config_v01_world_check():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                          "micro_batch_sizes": [2, 4, 6], "version": 0.1}}
    batch, valid = compute_elastic_config(cfg)
    assert batch == 1680
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(cfg, world_size=11)


@pytest.mark.parametrize("bad, match", [
    ({"micro_batch_sizes": []}, "micro_batch_sizes"),
    ({"micro_batch_sizes": [0, 2]}, "micro_batch_sizes"),
    ({"micro_batch_sizes": [2, "four"]}, "micro_batch_sizes"),
    ({"max_train_batch_size": 2, "micro_batch_sizes": [4, 8]},
     "max_train_batch_size"),
    ({"min_gpus": 0}, "min_gpus"),
    ({"min_gpus": 8, "max_gpus": 4}, "max_gpus"),
    ({"version": 0.3}, "version"),
    ({"version": "latest"}, "version"),
    ({"model_parallel_size": 0}, "model_parallel_size"),
    ({"model_parallel_size": 2, "version": 0.1}, "model parallelism"),
    ({"num_gpus_per_node": 0}, "num_gpus_per_node"),
    ({"num_gpus_per_node": 3, "model_parallel_size": 2},
     "divisible by"),
])
def test_validate_rejects_inconsistent_configs(bad, match):
    """Satellite: inconsistent elasticity configs fail fast with a
    descriptive error instead of blowing up mid-run on a resize."""
    with pytest.raises(ElasticityConfigError, match=match):
        validate_elastic_config(bad)


def test_validate_accepts_defaults_and_good_configs():
    validate_elastic_config({})
    validate_elastic_config({"micro_batch_sizes": [2, 4],
                             "max_train_batch_size": 64, "version": 0.1,
                             "min_gpus": 1, "max_gpus": 16})
    # integral floats (JSON/YAML 2e3-style literals) keep working — the
    # batch arithmetic always handled them; only non-integral rejects
    validate_elastic_config({"micro_batch_sizes": [2.0, 4],
                             "max_train_batch_size": 2000.0,
                             "min_gpus": 1.0, "max_gpus": 16.0})
    # numpy scalars from array-derived configs keep working too
    validate_elastic_config({"micro_batch_sizes": list(np.array([2, 4])),
                             "max_train_batch_size": np.int64(2000),
                             "min_gpus": np.float64(1.0)})
    with pytest.raises(ElasticityConfigError, match="max_train_batch_size"):
        validate_elastic_config({"max_train_batch_size": 100.5})


def test_compute_elastic_config_validates_up_front():
    cfg = {"elasticity": {"enabled": True, "micro_batch_sizes": [],
                          "max_train_batch_size": 100}}
    with pytest.raises(ElasticityConfigError, match="micro_batch_sizes"):
        compute_elastic_config(cfg, world_size=8)


def test_initialize_rejects_bad_elastic_config(devices8):
    """The engine surfaces elasticity config errors at initialize() time
    (satellite acceptance: descriptive error, not a mid-run failure)."""
    cfg = elastic_engine_config()
    cfg["elasticity"]["micro_batch_sizes"] = [2, -4]
    with pytest.raises(ElasticityConfigError, match="micro_batch_sizes"):
        deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)
    cfg = elastic_engine_config()
    cfg["elasticity"]["num_gpus_per_node"] = 3
    cfg["elasticity"]["model_parallel_size"] = 2
    with pytest.raises(ElasticityConfigError, match="divisible by"):
        deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)


def elastic_engine_config():
    return {
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": -1, "fsdp": 1},
        "steps_per_print": 10**9,
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 96,
            "micro_batch_sizes": [2, 4],
            "version": 0.2,
            "ignore_non_elastic_batch_info": True,
        },
    }


def test_engine_adopts_elastic_batch(devices8):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=elastic_engine_config())
    cfg = engine.config
    dp = engine.topology.get_data_parallel_world_size()
    assert cfg.train_batch_size == \
        cfg.train_micro_batch_size_per_gpu * cfg.gradient_accumulation_steps * dp
    assert cfg.train_micro_batch_size_per_gpu in (2, 4)
    assert cfg.train_batch_size <= 96


def test_engine_rejects_explicit_batch_with_elasticity(devices8):
    cfg = elastic_engine_config()
    cfg["train_micro_batch_size_per_gpu"] = 4
    cfg["elasticity"]["ignore_non_elastic_batch_info"] = False
    with pytest.raises(Exception, match="elasticity"):
        deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)


def test_elastic_restart_scale_down(tmp_path, devices8):
    """Checkpoint on the 8-chip mesh, resume on a 4-chip mesh: the elastic
    global batch is unchanged (gas doubles), params match bit-for-bit, and
    training continues finitely — the reference's restart-based elastic
    scale-down (DSElasticAgent role) driven through universal checkpoints."""
    def run(engine, steps, seed=0):
        rng = np.random.default_rng(seed)
        dp = engine.topology.get_data_parallel_world_size()
        micro = engine.config.train_micro_batch_size_per_gpu
        batch = {"input_ids": rng.integers(
            0, 256, size=(micro * dp, 33), dtype=np.int64)}
        return [float(engine.train_batch(itertools.repeat(batch)))
                for _ in range(steps)]

    e8, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=elastic_engine_config())
    batch8 = e8.config.train_batch_size
    run(e8, 2)
    e8.save_checkpoint(str(tmp_path))
    ref = [np.asarray(l) for l in jax.tree.leaves(e8.state.params)]

    topo.reset_topology()
    mesh4 = topo.MeshTopology.build(None, devices=jax.devices()[:4])
    e4, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=elastic_engine_config(), mesh=mesh4)
    assert e4.config.train_batch_size == batch8       # global batch invariant
    assert e4.config.gradient_accumulation_steps == \
        2 * e8.config.gradient_accumulation_steps
    e4.load_checkpoint(str(tmp_path))
    for a, b in zip(ref, jax.tree.leaves(e4.state.params)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)
    losses = run(e4, 2, seed=7)
    assert np.isfinite(losses).all()
