"""1-bit optimizer tests (reference tests/unit/runtime/half_precision/onebit/
test_onebit.py): warmup-phase exact Adam parity, compressed-phase convergence,
error-feedback correctness, and the int8 wire format showing up in the
compiled collective."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import build_model
from deepspeed_tpu.ops.onebit import (OneBitAdam, OneBitLamb, ZeroOneAdam,
                                      _sign_compress_psum)
from deepspeed_tpu.ops.optimizers import build_optimizer


def tiny_data(n=64, seq=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(n, seq + 1),
                                      dtype=np.int64)}


def make_config(opt_type, freeze_step, **opt_extra):
    params = {"lr": 1e-3, "freeze_step": freeze_step}
    if opt_type == "ZeroOneAdam":
        params = {"lr": 1e-3, "var_freeze_step": freeze_step}
    params.update(opt_extra)
    return {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": opt_type, "params": params},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": -1, "fsdp": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 100,
    }


def run_steps(engine, data, steps):
    loader = deepspeed_tpu.runtime.dataloader.RepeatingLoader(
        engine.deepspeed_io(data))
    it = iter(loader)
    losses = []
    for _ in range(steps):
        for _ in range(engine.gradient_accumulation_steps()):
            loss = engine(next(it))
            engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def test_registry_builds_real_onebit():
    assert isinstance(build_optimizer("OneBitAdam", {"lr": 1e-3}), OneBitAdam)
    assert isinstance(build_optimizer("ZeroOneAdam", {"lr": 1e-3}),
                      ZeroOneAdam)
    assert isinstance(build_optimizer("OneBitLamb", {"lr": 1e-3}), OneBitLamb)


def test_sign_compress_roundtrip_error_feedback(devices8):
    """avg + per-worker err must exactly decompose each worker's input:
    c_i = sign(c_i)·scale_i + err_i, and avg = mean_i sign(c_i)·scale_i."""
    from deepspeed_tpu.compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devices8), ("data",))
    x = jax.device_put(
        np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32),
        NamedSharding(mesh, P("data")))

    def f(x):
        return _sign_compress_psum(x, 8)

    avg, err = shard_map(f, mesh=mesh, in_specs=P("data"),
                         out_specs=(P(), P("data")), check_vma=False)(x)
    xs = np.asarray(x)
    scale = np.abs(xs).mean(axis=1).mean()      # shared scale over workers
    recon = np.where(xs >= 0, 1.0, -1.0) * scale
    np.testing.assert_allclose(np.asarray(avg)[0], recon.mean(0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(err), xs - recon,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_warmup_matches_plain_adam(devices8):
    """With freeze_step beyond the horizon, OneBitAdam must be exact Adam."""
    data = tiny_data()
    cfg_1bit = make_config("OneBitAdam", freeze_step=1000)
    cfg_adam = dict(cfg_1bit)
    cfg_adam["optimizer"] = {"type": "Adam", "params": {"lr": 1e-3}}

    e1, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                           config=cfg_1bit)
    run_steps(e1, data, steps=3)
    e2, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                           config=cfg_adam)
    run_steps(e2, data, steps=3)
    p1 = jax.tree.leaves(jax.device_get(e1.state.params))
    p2 = jax.tree.leaves(jax.device_get(e2.state.params))
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-6)


@pytest.mark.parametrize("opt_type", ["OneBitAdam", "ZeroOneAdam",
                                      "OneBitLamb"])
def test_compressed_phase_trains(opt_type, devices8):
    """Short warmup then compressed steps: loss keeps decreasing and the
    compiled compressed update moves packed sign bits (u8) through the
    two-phase all_to_all + all_gather wire."""
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config(opt_type, freeze_step=2))
    losses = run_steps(engine, tiny_data(), steps=8)
    assert engine._onebit
    assert np.isfinite(losses).all()
    assert min(losses[3:]) < losses[0], f"no progress post-freeze: {losses}"

    from deepspeed_tpu.utils.comms_logging import analyze_compiled

    report = analyze_compiled(jax.jit(engine._update_raw).lower(
        jax.eval_shape(lambda s: s, engine.state)).compile())
    assert "all-to-all" in report, report
    assert "u8" in report["all-to-all"]["dtypes"], report
    assert "u8" in report["all-gather"]["dtypes"], report
    warm = jax.jit(engine._update_warm_raw).lower(
        jax.eval_shape(lambda s: s, engine.state)).as_text()
    # warmup phase all-reduces full-precision f32 gradients instead
    assert "i8" not in warm and "all_to_all" not in warm


def test_packed_wire_bytes_beat_int8(devices8):
    """VERDICT r3 weak #5: the packed two-phase wire must move ~4x fewer
    collective-operand bytes than the int8 sign psum (1/4 vs 1 byte per
    element; in ring-link terms the all-reduce pays another 2x, making the
    end-to-end reduction ~8x and the fp32 baseline ~32x)."""

    from deepspeed_tpu.utils.comms_logging import analyze_compiled

    def wire_bytes(wire_bits):
        from deepspeed_tpu.parallel import topology as topo

        topo.reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_model("tiny"),
            config=make_config("OneBitAdam", freeze_step=2,
                               wire_bits=wire_bits))
        report = analyze_compiled(jax.jit(engine._update_raw).lower(
            jax.eval_shape(lambda s: s, engine.state)).compile())
        return sum(rec["bytes"] for rec in report.values())

    b8, b1 = wire_bytes(8), wire_bytes(1)
    assert b1 < b8 / 3.5, f"packed wire {b1}B vs int8 {b8}B — expected >3.5x"


def test_packed_and_int8_wires_both_converge(devices8):
    """Numeric sanity across wire formats with an adequate warmup (the
    reference defaults freeze_step to 100k for a reason — freezing the
    variance after 2 steps diverges under EITHER wire): both formats must
    end clearly below the starting loss on a memorizable batch."""
    results = {}
    for wb in (1, 8):
        from deepspeed_tpu.parallel import topology as topo

        topo.reset_topology()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_model("tiny"),
            config=make_config("OneBitAdam", freeze_step=6, wire_bits=wb))
        results[wb] = run_steps(engine, tiny_data(), steps=14)
    for wb, losses in results.items():
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] - 0.25, (wb, losses)


def test_variance_frozen_after_freeze(devices8):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config=make_config("OneBitAdam", freeze_step=1))
    run_steps(engine, tiny_data(), steps=1)   # warmup step builds v
    v_before = jax.device_get(engine.state.opt_state.moments["v"])
    run_steps(engine, tiny_data(seed=1), steps=3)
    v_after = jax.device_get(engine.state.opt_state.moments["v"])
    for a, b in zip(jax.tree.leaves(v_before), jax.tree.leaves(v_after)):
        np.testing.assert_array_equal(a, b)


def test_onebit_rejects_model_parallel_mesh(devices8):
    cfg = make_config("OneBitAdam", freeze_step=2)
    cfg["mesh"] = {"data": -1, "fsdp": 2}
    with pytest.raises(ValueError, match="pure data parallel"):
        deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)


def test_onebit_rejects_zero_stage_2(devices8):
    cfg = make_config("OneBitAdam", freeze_step=2)
    cfg["zero_optimization"] = {"stage": 2}
    with pytest.raises(ValueError, match="stage <= 1"):
        deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)


@pytest.mark.slow
def test_onebit_checkpoint_roundtrip(tmp_path, devices8):
    """Error-feedback moments (dp-leading, data-sharded) survive a
    save/load round trip and training continues identically."""
    data = tiny_data()
    e1, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config("OneBitAdam",
                                                      freeze_step=1))
    run_steps(e1, data, steps=3)           # into the compressed phase
    e1.save_checkpoint(str(tmp_path))
    ref_e = [np.asarray(l) for l in
             jax.tree.leaves(e1.state.opt_state.moments["e"])]

    e2, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config("OneBitAdam",
                                                      freeze_step=1))
    e2.load_checkpoint(str(tmp_path))
    for a, b in zip(ref_e,
                    jax.tree.leaves(e2.state.opt_state.moments["e"])):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)
    # restored counter keeps e2 past freeze → compressed path, same as e1
    assert e2.global_steps == e1.global_steps
    a = run_steps(e1, tiny_data(seed=3), steps=2)
    b = run_steps(e2, tiny_data(seed=3), steps=2)
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_two_phase_error_feedback_invariants(devices8):
    """Unit contract of the packed two-phase wire (nccl.py:16 semantics):
    worker error = c − sign(c)·scale exactly, and per-segment
    avg + server_error == phase-1 mean exactly (the server compression is
    lossless once its residual is carried)."""
    from functools import partial

    from deepspeed_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.ops.onebit import _sign_compress_two_phase

    dp = 8
    n = 100                                    # deliberately not 8*dp-aligned
    mesh = Mesh(np.array(jax.devices()), ("data",))
    rng = np.random.default_rng(0)
    cs = jnp.asarray(rng.standard_normal((dp, n)), jnp.float32)
    seg = -(-n // (dp * 8)) * 8
    e2 = jnp.zeros((dp, seg), jnp.float32)

    def local(c, e):
        avg, err, e2n = _sign_compress_two_phase(c[0], e[0], dp)
        return avg[None], err[None], e2n[None]

    avg, err, e2n = shard_map(
        local, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data")), check_vma=False)(cs, e2)
    avg, err, e2n = map(np.asarray, (avg, err, e2n))

    # worker error: exact residual of the local compression (RMS scale —
    # the reference's worker_scale ‖c‖/√numel, nccl.py compressed_allreduce)
    for i in range(dp):
        scale = np.sqrt(np.mean(np.asarray(cs[i]) ** 2))
        q = np.where(np.asarray(cs[i]) >= 0, scale, -scale)
        np.testing.assert_allclose(err[i], np.asarray(cs[i]) - q,
                                   rtol=1e-5, atol=1e-6)

    # every worker reconstructs the same average
    for i in range(1, dp):
        np.testing.assert_array_equal(avg[0], avg[i])

    # avg + server error == phase-1 mean (pad positions excluded)
    scales = np.array([np.sqrt(np.mean(np.asarray(cs[i]) ** 2))
                       for i in range(dp)])
    signs = np.where(np.asarray(cs) >= 0, 1.0, -1.0)
    phase1 = np.zeros(seg * dp, np.float32)
    phase1[:n] = np.mean(signs * scales[:, None], axis=0)
    full_e2 = e2n.reshape(-1)[:n]
    np.testing.assert_allclose(avg[0] + full_e2, phase1[:n],
                               rtol=1e-5, atol=1e-6)
