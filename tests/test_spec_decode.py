"""Speculative decoding (ISSUE 3): greedy-lossless verification, KV
rollback via trim_sequence (block-boundary and prefix-cache edge cases),
n-gram and draft-model proposers, scheduler/serving/config wiring. The
hard guarantee throughout: greedy token streams are byte-identical with
speculation on and off, and spec off is byte-for-byte the old engine."""

import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.ragged import DSStateManager
from deepspeed_tpu.inference.v2.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.inference.v2.spec import (DraftModelProposer,
                                             NGramProposer, verify_greedy)
from deepspeed_tpu.inference.v2.testing import (assert_greedy_parity,
                                                greedy_generate, spec_summary)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

VOCAB = 128
BS = 8          # kv block size used throughout


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=2,
                            max_seq_len=128, norm="rmsnorm",
                            activation="silu", position="rope")
    model = CausalLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def make_engine(model, params, prefix=False, kv_blocks=64, max_seqs=4,
                chunk=32):
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=chunk, kv_blocks=kv_blocks, kv_block_size=BS,
        max_tracked_sequences=64, enable_prefix_cache=prefix)
    return InferenceEngineV2(model, params=params, config=vcfg)


def model_cfg():
    return TransformerConfig(vocab_size=VOCAB, hidden_size=16,
                             intermediate_size=32, num_layers=1, num_heads=2,
                             max_seq_len=256, norm="rmsnorm",
                             activation="silu", position="rope")


def tiny_manager(enabled=False, num_blocks=16):
    return DSStateManager(model_cfg(), 32, num_blocks, BS,
                          enable_prefix_cache=enabled)


def repetitive_prompts(rng, n=3, motif_len=5, reps=4, tail=3):
    """Motif-repetition prompts: greedy decode settles into the loop, so
    the n-gram proposer's drafts are mostly accepted."""
    out = []
    for _ in range(n):
        motif = rng.integers(0, VOCAB, size=motif_len).tolist()
        out.append(motif * reps + rng.integers(0, VOCAB, size=tail).tolist())
    return out


# ------------------------------------------------------------- verify unit
def test_verify_greedy_accepts_agreeing_prefix():
    V = 16

    def rows_for(next_tokens):
        r = np.zeros((len(next_tokens), V), np.float32)
        for i, t in enumerate(next_tokens):
            r[i, t] = 1.0
        return r

    # target would continue 7→5→9; drafts [5, 9] fully agree
    emitted, last = verify_greedy([7, 5, 9], rows_for([5, 9, 3]))
    assert emitted == [7, 5, 9] and last == 2
    # first draft wrong: only the certain token survives
    emitted, last = verify_greedy([7, 4, 9], rows_for([5, 9, 3]))
    assert emitted == [7] and last == 0
    # mid-chunk rejection: agreement stops after one draft
    emitted, last = verify_greedy([7, 5, 1], rows_for([5, 9, 3]))
    assert emitted == [7, 5] and last == 1
    # no drafts: plain decode row
    emitted, last = verify_greedy([7], rows_for([5]))
    assert emitted == [7] and last == 0


# ------------------------------------------------------------ ngram unit
def test_ngram_prefers_longest_suffix_and_recent_match():
    p = NGramProposer(ngram_max=3)
    # suffix [1,2] occurs twice; the later occurrence is followed by 9
    ctx = [1, 2, 7, 5, 1, 2, 9, 3, 1, 2]
    assert p.propose(0, ctx, 2) == [9, 3]
    # longer suffix wins over a shorter, more recent one
    ctx2 = [5, 1, 2, 3, 8, 2, 3, 0, 5, 1, 2, 3]
    assert p.propose(0, ctx2, 1) == [8]       # trigram [1,2,3] match
    assert p.propose(0, [1, 2, 3], 0) == []   # k=0
    assert p.propose(0, [4], 3) == []         # nothing to match
    assert p.propose(0, list(range(9)), 3) == []  # no repeat → no draft


def test_ngram_k_cap_and_history_window():
    p = NGramProposer(ngram_max=2, max_history=8)
    ctx = [1, 2, 3, 4, 5, 6, 1, 2]
    assert p.propose(0, ctx, 10) == [3, 4, 5, 6, 1, 2]   # capped by history
    # the matching occurrence fell outside the window → no proposal
    p2 = NGramProposer(ngram_max=2, max_history=4)
    assert p2.propose(0, ctx, 4) == []
    with pytest.raises(ValueError):
        NGramProposer(ngram_max=0)


# ----------------------------------------------------- trim_sequence unit
def _fill(mgr, uid, tokens):
    seq = mgr.get_or_create_sequence(uid)
    mgr.maybe_allocate_kv(seq, len(tokens))
    seq.seen_tokens += len(tokens)
    mgr.record_tokens(seq, tokens)
    return seq


def test_trim_across_block_boundary():
    mgr = tiny_manager()
    seq = _fill(mgr, 1, list(range(20)))        # 2 full blocks + 4 in third
    assert len(seq.kv_blocks) == 3
    assert mgr.trim_sequence(1, 6) == 1         # 20→14: third block empties
    assert seq.seen_tokens == 14
    assert len(seq.kv_blocks) == 2
    assert mgr.free_blocks == 16 - 2


def test_trim_to_exact_block_edge():
    mgr = tiny_manager()
    seq = _fill(mgr, 1, list(range(20)))
    assert mgr.trim_sequence(1, 4) == 1         # 20→16: exactly 2 blocks
    assert seq.seen_tokens == 16
    assert len(seq.kv_blocks) == 2
    # trimming zero more is a no-op; a fresh token reuses a new block
    assert mgr.trim_sequence(1, 0) == 0
    mgr.maybe_allocate_kv(seq, 1)
    assert len(seq.kv_blocks) == 3


def test_trim_entire_sequence_and_overtrim():
    mgr = tiny_manager()
    seq = _fill(mgr, 1, list(range(12)))
    assert mgr.trim_sequence(1, 12) == 2
    assert seq.seen_tokens == 0 and seq.kv_blocks == []
    assert mgr.free_blocks == 16
    with pytest.raises(ValueError, match="cannot trim"):
        mgr.trim_sequence(1, 1)
    assert mgr.trim_sequence(99, 3) == 0        # unknown uid: no-op


def test_trim_spares_prefix_shared_blocks():
    """Trim of a sequence whose EARLIER blocks are prefix-shared: only its
    private trailing blocks are released; shared refcounts are untouched."""
    mgr = tiny_manager(enabled=True)
    toks = list(range(16))
    _fill(mgr, 1, toks)                        # donor indexes 2 blocks
    matched = mgr.match_prefix(2, toks + [7, 7, 7])
    assert matched == 16
    seq2 = mgr.get_sequence(2)
    shared = list(seq2.kv_blocks)
    # sharer extends into private blocks (as a speculative step would)
    mgr.maybe_allocate_kv(seq2, 6)
    seq2.seen_tokens += 6                      # e.g. 1 certain + 5 drafts
    assert len(seq2.kv_blocks) == 3
    private = seq2.kv_blocks[2]
    assert mgr.trim_sequence(2, 5) == 0        # 22→17: block 3 still needed
    assert mgr.trim_sequence(2, 1) == 1        # 17→16: private block freed
    assert seq2.kv_blocks == shared
    for b in shared:
        assert mgr.allocator.ref_count(b) == 3  # cache + donor + sharer
    assert mgr.allocator.ref_count(private) == 0
    # trimming INTO the shared (indexed) blocks must refuse: their content
    # is immutable while the index / the donor reference it
    with pytest.raises(ValueError, match="prefix-indexed"):
        mgr.trim_sequence(2, 1)


def test_trim_drops_pending_chain_tokens():
    """Un-blocked pending hash-chain tokens past the trim point must be
    dropped, so a later record_tokens stays position-consistent."""
    mgr = tiny_manager(enabled=True)
    seq = _fill(mgr, 1, list(range(12)))       # 1 full block + 4 pending
    assert len(seq.pending_tokens) == 4
    mgr.trim_sequence(1, 2)                    # 12→10
    assert seq.pending_tokens == [8, 9]
    mgr.record_tokens(seq, [])                 # consistency guard happy
    seq.seen_tokens += 6
    mgr.record_tokens(seq, [10, 11, 12, 13, 14, 15])
    assert seq.hashed_blocks == 2              # chain advanced cleanly
    mgr.flush_sequence(1)
    assert mgr.match_prefix(3, list(range(10)) + [10, 11, 12, 13, 14, 15, 0]
                            ) == 16


def test_trim_never_registers_draft_tokens(model_and_params):
    """A speculative put (defer_commit) followed by trim + commit must
    leave the prefix index with exactly the accepted tokens — a later
    prompt matching the REJECTED continuation must miss."""
    model, params = model_and_params
    engine = make_engine(model, params, prefix=True)
    base = list(range(10, 24))                  # 14 accepted context tokens
    engine.put([1], [base], verify_width=4, defer_commit=True)
    # feed 1 certain + 3 drafts; pretend only the certain token survived
    engine.put([1], [[30, 31, 32, 33]], verify_width=4, defer_commit=True)
    engine.trim_sequence(1, 3)
    engine.commit_tokens(1, base + [30])        # accepted prefix only
    seq = engine.state_manager.get_sequence(1)
    assert seq.seen_tokens == 15
    assert seq.hashed_blocks == 1               # one full block of 8
    engine.flush(1)
    # the indexed block covers base[:8] only — drafts never entered it
    assert engine.state_manager.match_prefix(2, base[:8] + [99]) == 8
    assert engine.state_manager.match_prefix(
        3, base + [30, 31, 32, 33, 99]) == 8    # nothing past block 1


# ----------------------------------------------------- scheduler parity
def test_spec_parity_high_acceptance(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(0)
    prompts = repetitive_prompts(rng)
    base = greedy_generate(make_engine(model, params), prompts,
                           uid_base=100, max_new_tokens=12)
    sched = ContinuousBatchingScheduler(
        make_engine(model, params), proposer=NGramProposer(ngram_max=3),
        max_draft_tokens=4)
    spec = greedy_generate(prompts=prompts, uid_base=100, max_new_tokens=12,
                           scheduler=sched)
    assert_greedy_parity(base, spec, "ngram speculation")
    stats = sched.spec_stats()
    assert stats["accepted"] > 0
    # speculation must actually reduce forwards on this workload
    assert spec_summary(stats)["tokens_per_forward"] > 1.5


def test_spec_parity_with_mid_stream_rejections(model_and_params):
    """Random prompts with small n-grams: proposals fire but are often
    wrong — the stream must still be byte-identical, with KV rolled back
    at every rejection."""
    model, params = model_and_params
    rng = np.random.default_rng(7)
    # low-entropy alphabet → suffix matches (and wrong continuations) abound
    prompts = [rng.integers(0, 6, size=20).tolist() for _ in range(4)]
    base = greedy_generate(make_engine(model, params), prompts,
                           uid_base=200, max_new_tokens=16)
    sched = ContinuousBatchingScheduler(
        make_engine(model, params),
        proposer=NGramProposer(ngram_max=2, ngram_min=1),
        max_draft_tokens=4)
    spec = greedy_generate(prompts=prompts, uid_base=200, max_new_tokens=16,
                           scheduler=sched)
    assert_greedy_parity(base, spec, "ngram speculation (rejections)")
    stats = sched.spec_stats()
    assert stats["proposed"] > 0
    assert stats["accepted"] < stats["proposed"], (
        f"workload produced no rejections — not exercising rollback: "
        f"{stats}")


def test_spec_parity_draft_model(model_and_params):
    """Draft model == target model: every draft verifies, every forward
    emits max_draft_tokens+1 — and the stream is still byte-identical."""
    model, params = model_and_params
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, VOCAB, size=12).tolist() for _ in range(2)]
    base = greedy_generate(make_engine(model, params), prompts,
                           uid_base=300, max_new_tokens=9)
    draft = DraftModelProposer(make_engine(model, params))
    sched = ContinuousBatchingScheduler(make_engine(model, params),
                                        proposer=draft, max_draft_tokens=4)
    spec = greedy_generate(prompts=prompts, uid_base=300, max_new_tokens=9,
                           scheduler=sched)
    assert_greedy_parity(base, spec, "draft-model speculation")
    stats = sched.spec_stats()
    assert stats["accepted"] == stats["proposed"]   # perfect draft
    assert spec_summary(stats)["tokens_per_forward"] > 2.0
    # draft KV is reclaimed when sequences finish (release() flushes)
    assert draft.engine.free_blocks == draft.engine.config.kv_blocks


def test_spec_respects_max_new_tokens_and_concurrency(model_and_params):
    """Drafts are capped so a request never emits past max_new_tokens,
    including when several requests run concurrently (SplitFuse-packed
    speculative rows)."""
    model, params = model_and_params
    rng = np.random.default_rng(5)
    prompts = repetitive_prompts(rng, n=4)
    base = greedy_generate(make_engine(model, params), prompts,
                           uid_base=400, max_new_tokens=7)
    sched = ContinuousBatchingScheduler(
        make_engine(model, params), proposer=NGramProposer(),
        max_draft_tokens=6)
    spec = greedy_generate(prompts=prompts, uid_base=400, max_new_tokens=7,
                           scheduler=sched, sequential=False)
    assert_greedy_parity(base, spec, "concurrent speculation")
    assert all(len(g) == 7 for g in spec)


def test_spec_kv_pressure_degrades_to_plain_decode(model_and_params):
    """When the speculative chunk cannot be admitted (KV pool exhausted),
    the scheduler falls back to single-token decode instead of deferring
    the sequence; generation completes with identical tokens."""
    model, params = model_and_params
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, VOCAB, size=7).tolist()
    base = greedy_generate(make_engine(model, params), [prompt],
                           uid_base=500, max_new_tokens=8)
    # 2 blocks of 8 = 16 slots; prompt 7 + 8 new = 15 fits, but a 5-token
    # speculative chunk near the end would need a 3rd block → fallback
    sched = ContinuousBatchingScheduler(
        make_engine(model, params, kv_blocks=2),
        proposer=DraftModelProposer(make_engine(model, params)),
        max_draft_tokens=4)
    spec = greedy_generate(prompts=[prompt], uid_base=500, max_new_tokens=8,
                           scheduler=sched)
    assert_greedy_parity(base, spec, "KV-pressure fallback")


def test_spec_eos_mid_chunk(model_and_params):
    """EOS landing inside an accepted draft run must finish the request at
    exactly the token where plain decoding would have stopped."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    prompts = repetitive_prompts(rng, n=1)
    base = greedy_generate(make_engine(model, params), prompts,
                           uid_base=600, max_new_tokens=12)
    # pick a token the stream emits mid-way as EOS
    eos = base[0][5]
    base_eos = greedy_generate(make_engine(model, params), prompts,
                               uid_base=610, max_new_tokens=12,
                               eos_token_id=eos)
    sched = ContinuousBatchingScheduler(
        make_engine(model, params), proposer=NGramProposer(),
        max_draft_tokens=4)
    spec_eos = greedy_generate(prompts=prompts, uid_base=610,
                               max_new_tokens=12, eos_token_id=eos,
                               scheduler=sched)
    assert_greedy_parity(base_eos, spec_eos, "EOS mid-chunk")
    assert spec_eos[0][-1] == eos
    assert sched.finished[610].finish_reason == "eos"


def test_trim_refuses_shared_unindexed_block():
    """Sharing is only legal through the prefix index; a trim that would
    drop a block some other holder shares outside it must refuse (that
    holder would be reading rolled-back KV)."""
    mgr = tiny_manager()
    seq = _fill(mgr, 1, list(range(12)))
    mgr.allocator.share([seq.kv_blocks[1]])     # rogue out-of-index share
    with pytest.raises(ValueError, match="sharing invariant"):
        mgr.trim_sequence(1, 6)                 # would drop block 1
    assert seq.seen_tokens == 12                # refused: nothing changed


def test_spec_parity_draft_engine_with_prefix_cache(model_and_params):
    """A prefix-cache-enabled DRAFT engine must not break rollback: draft
    feeds defer the hash chain, so trimming rejected drafts never hits
    indexed blocks — rejections included, streams identical."""
    model, params = model_and_params
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, 6, size=20).tolist() for _ in range(3)]
    base = greedy_generate(make_engine(model, params), prompts,
                           uid_base=900, max_new_tokens=16)
    draft = DraftModelProposer(make_engine(model, params, prefix=True))
    sched = ContinuousBatchingScheduler(make_engine(model, params),
                                        proposer=draft, max_draft_tokens=4)
    spec = greedy_generate(prompts=prompts, uid_base=900, max_new_tokens=16,
                           scheduler=sched)
    assert_greedy_parity(base, spec, "prefix-cached draft engine")
    assert not sched._proposer_warned           # no swallowed faults
    assert not draft.engine.state_manager._index    # chain never advanced


def test_faulty_proposer_degrades_not_crashes(model_and_params):
    """Proposers are advisory: one that raises must cost only its drafts
    — generation completes with the exact greedy stream."""
    model, params = model_and_params

    class Boom:
        calls = 0

        def propose(self, uid, context, k):
            Boom.calls += 1
            raise RuntimeError("draft engine fell over")

        def release(self, uid):
            pass

    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, VOCAB, size=10).tolist() for _ in range(2)]
    base = greedy_generate(make_engine(model, params), prompts,
                           uid_base=950, max_new_tokens=6)
    sched = ContinuousBatchingScheduler(make_engine(model, params),
                                        proposer=Boom(), max_draft_tokens=4)
    out = greedy_generate(prompts=prompts, uid_base=950, max_new_tokens=6,
                          scheduler=sched)
    assert_greedy_parity(base, out, "faulty proposer")
    assert Boom.calls > 0 and sched._proposer_warned


def test_custom_sampler_disables_speculation(model_and_params):
    model, params = model_and_params
    sched = ContinuousBatchingScheduler(
        make_engine(model, params),
        sample_fn=lambda logits: int(np.argmax(logits)),
        proposer=NGramProposer())
    assert not sched.spec_enabled               # lossless only under greedy
    # ...and the serving layer never builds the doomed proposer at all
    from deepspeed_tpu.serving import Replica, SpeculativeConfig

    class TrapSpec(SpeculativeConfig):
        def build_proposer(self, draft_engine_factory=None):
            raise AssertionError("proposer built despite custom sample_fn")

    rep = Replica(0, make_engine(model, params),
                  sample_fn=lambda logits: int(np.argmax(logits)),
                  speculative=TrapSpec(enabled=True))
    assert not rep.scheduler.spec_enabled


def test_cancel_mid_speculation(model_and_params):
    """Cancel while drafts are in flight: target KV is freed immediately,
    the proposer's per-uid state (draft-model KV included) is released."""
    model, params = model_and_params
    rng = np.random.default_rng(9)
    prompts = repetitive_prompts(rng, n=2)
    draft = DraftModelProposer(make_engine(model, params))
    engine = make_engine(model, params)
    sched = ContinuousBatchingScheduler(engine, proposer=draft,
                                        max_draft_tokens=4)
    for i, p in enumerate(prompts):
        sched.submit(700 + i, p, max_new_tokens=16)
    steps = 0
    while sched.has_work and steps < 100:
        sched.step()
        steps += 1
        if steps == 2:
            assert sched.cancel(700)
    assert sched.finished[700].finish_reason == "cancelled"
    assert sched.finished[701].finish_reason in ("length", "eos")
    assert 700 not in draft._fed                # proposer state released
    assert engine.free_blocks == engine.config.kv_blocks
    assert draft.engine.free_blocks == draft.engine.config.kv_blocks


# ------------------------------------------------------- serving wiring
def test_serving_config_enables_speculation(model_and_params):
    """`serving: {speculative: {enabled: true}}` must wire a per-replica
    proposer and surface acceptance counters in the metrics registry —
    with generations identical to a spec-off frontend run."""
    from deepspeed_tpu.serving import (ServingConfig, ServingFrontend,
                                       SpeculativeConfig)

    model, params = model_and_params
    rng = np.random.default_rng(2)
    prompt = repetitive_prompts(rng, n=1)[0]
    base = greedy_generate(make_engine(model, params), [prompt],
                           uid_base=800, max_new_tokens=10)

    engine = make_engine(model, params)
    cfg = ServingConfig(max_queue_depth=8,
                        speculative=SpeculativeConfig(enabled=True,
                                                      mode="ngram",
                                                      max_draft_tokens=4))
    fe = ServingFrontend([engine], cfg)
    try:
        replica = fe.router.replicas[0]
        assert replica.scheduler.spec_enabled
        assert isinstance(replica.scheduler.proposer, NGramProposer)
        h = fe.submit(prompt, max_new_tokens=10)
        assert h._req.wait(60)
        tokens = [ev.token for ev in h.stream(timeout=10)]
        assert tokens == base[0]
        snap = fe.metrics_snapshot()
        assert snap["spec_tokens_proposed"] > 0
        assert snap["spec_tokens_emitted"] > snap["spec_decode_forwards"]
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_deadline_expiry_mid_speculation(model_and_params):
    """A deadline firing while a request is mid-speculation cancels it
    between steps; its KV (and any rejected-draft bookkeeping) is fully
    reclaimed and other requests are unaffected."""
    from deepspeed_tpu.serving import (RequestState, ServingConfig,
                                       ServingFrontend, SpeculativeConfig)

    model, params = model_and_params
    rng = np.random.default_rng(4)
    prompt = repetitive_prompts(rng, n=1)[0]
    engine = make_engine(model, params)
    cfg = ServingConfig(max_queue_depth=8,
                        speculative=SpeculativeConfig(enabled=True,
                                                      max_draft_tokens=4))
    fe = ServingFrontend([engine], cfg)
    try:
        doomed = fe.submit(prompt, max_new_tokens=100, deadline_ms=150.0)
        ok = fe.submit(prompt, max_new_tokens=6)
        assert doomed._req.wait(60) and ok._req.wait(60)
        assert doomed.state == RequestState.EXPIRED
        assert ok.state == RequestState.FINISHED
        deadline = time.monotonic() + 10
        while engine.free_blocks != engine.config.kv_blocks \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert engine.free_blocks == engine.config.kv_blocks
        assert fe.metrics_snapshot()["requests_expired"] == 1
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_speculative_config_build_proposer(model_and_params):
    from deepspeed_tpu.serving import SpeculativeConfig

    model, params = model_and_params
    assert SpeculativeConfig().build_proposer() is None
    p = SpeculativeConfig(enabled=True, ngram_max=5).build_proposer()
    assert isinstance(p, NGramProposer) and p.ngram_max == 5
    dm = SpeculativeConfig(enabled=True, mode="draft_model").build_proposer(
        draft_engine_factory=lambda: make_engine(model, params))
    assert isinstance(dm, DraftModelProposer)
    with pytest.raises(ValueError, match="draft_model"):
        SpeculativeConfig(enabled=True,
                          mode="draft_model").build_proposer()
    with pytest.raises(ValueError, match="unknown speculative.mode"):
        SpeculativeConfig(enabled=True, mode="magic").build_proposer()
