"""Sparse attention tests (reference tests/unit/ops/sparse_attention/):
layout-builder semantics per pattern, dense-layout parity with exact
attention, causal masking, padding masks, and gradient flow."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseSelfAttention, VariableSparsityConfig,
    sparse_attention)


def dense_attention(q, k, v, causal=False, key_padding_mask=None):
    D = q.shape[-1]
    scores = jnp.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
    T = q.shape[2]
    if causal:
        scores = jnp.where(jnp.tril(jnp.ones((T, T), bool)), scores, -1e30)
    if key_padding_mask is not None:
        scores = jnp.where(key_padding_mask[:, None, None, :], scores, -1e30)
    return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(scores, -1), v)


def rand_qkv(B=2, H=4, T=64, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    return mk(), mk(), mk()


# ----------------------------------------------------------------- layouts
def test_fixed_layout_local_and_global():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=4,
                              num_global_blocks=1)
    layout = cfg.make_layout(16 * 8)
    assert layout.shape == (2, 8, 8)
    # local window: block rows 0-3 see each other
    assert layout[0, :4, :4].all()
    assert not layout[0, 0, 5]          # outside window, not global
    # global: last block of each window is a column everyone sees
    assert layout[0, :, 3].all() and layout[0, :, 7].all()


def test_fixed_layout_unidirectional_is_lower_triangular():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(16 * 8)
    assert not np.triu(layout[0], k=1).any()


def test_bigbird_layout():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    layout = cfg.make_layout(16 * 8)
    assert layout[0, 0, :].all() and layout[0, :, 0].all()   # global ITC
    for row in range(1, 7):                                  # sliding window
        assert layout[0, row, row - 1:row + 2].all()
    assert layout[0].sum(-1).min() >= 3                      # + randoms


def test_longformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0, 5])
    layout = cfg.make_layout(16 * 8)
    assert layout[0, :, 0].all() and layout[0, 0, :].all()
    assert layout[0, :, 5].all() and layout[0, 5, :].all()


def test_variable_layout_windows_and_random():
    cfg = VariableSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                 local_window_blocks=[2, 4],
                                 global_block_indices=[0])
    layout = cfg.make_layout(16 * 8)
    assert layout[0, :2, :2].all()       # first window (2 blocks)
    assert layout[0, 2:6, 2:6].all()     # second window (4 blocks)
    assert layout[0, :, 0].all()         # global column


def test_different_layout_per_head():
    cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4,
                              different_layout_per_head=True,
                              num_different_global_patterns=4)
    layout = cfg.make_layout(16 * 8)
    assert not np.array_equal(layout[0], layout[1])
    same = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4)
    layout2 = same.make_layout(16 * 8)
    assert np.array_equal(layout2[0], layout2[3])


# ----------------------------------------------------------------- compute
@pytest.mark.parametrize("causal", [False, True])
def test_dense_layout_matches_exact_attention(causal):
    q, k, v = rand_qkv()
    layout = DenseSparsityConfig(num_heads=4, block=16).make_layout(64)
    if causal:
        layout = np.tril(layout)
    out = sparse_attention(q, k, v, layout, block=16, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_key_padding_mask():
    q, k, v = rand_qkv()
    mask = np.ones((2, 64), dtype=bool)
    mask[:, 48:] = False
    layout = DenseSparsityConfig(num_heads=4, block=16).make_layout(64)
    out = sparse_attention(q, k, v, layout, block=16,
                           key_padding_mask=jnp.asarray(mask))
    ref = dense_attention(q, k, v, key_padding_mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_sparse_pattern_restricts_attention():
    """A token outside every admitted block must receive zero weight: move
    one value vector and verify out-of-window queries don't change."""
    q, k, v = rand_qkv(T=128)
    cfg = BSLongformerSparsityConfig(num_heads=4, block=16,
                                     num_sliding_window_blocks=1,
                                     global_block_indices=[0])
    layout = cfg.make_layout(128)
    out1 = sparse_attention(q, k, v, layout, block=16)
    # perturb v in block 5; queries in block 2 (window = self only,
    # globals = block 0) must be unaffected
    v2 = v.at[:, :, 80:96, :].add(100.0)
    out2 = sparse_attention(q, k, v2, layout, block=16)
    np.testing.assert_allclose(np.asarray(out1)[:, :, 32:48],
                               np.asarray(out2)[:, :, 32:48], rtol=1e-5)
    assert not np.allclose(np.asarray(out1)[:, :, 80:96],
                           np.asarray(out2)[:, :, 80:96])


def test_gradients_flow():
    q, k, v = rand_qkv(T=64)
    cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2,
                              attention="unidirectional")
    layout = cfg.make_layout(64)

    def loss(q, k, v):
        return sparse_attention(q, k, v, layout, block=16, causal=True).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(gq)).sum() > 0


def test_sparse_self_attention_wrapper():
    q, k, v = rand_qkv()
    att = SparseSelfAttention(
        FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2,
                            attention="unidirectional"))
    out = att(q, k, v)
    assert out.shape == q.shape
    assert 64 in att._layouts


def test_causal_lm_sparse_attention_trains(devices8):
    """attention_impl='sparse' trains end-to-end; with a window covering
    the whole sequence it matches dense attention exactly."""
    import dataclasses

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import CausalLM, TINY_TEST

    sparse_cfg = dataclasses.replace(
        TINY_TEST, num_kv_heads=2, attention_impl="sparse",
        sparse_pattern="fixed", sparse_block=8, sparse_num_local_blocks=2,
        sparse_num_global_blocks=1)
    model = CausalLM(sparse_cfg)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": -1, "fsdp": 1},
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, size=(32, 33),
                                       dtype=np.int64)}
    import itertools as it
    losses = [float(engine.train_batch(it.repeat(batch))) for _ in range(5)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    # full-coverage window == dense reference forward
    wide = dataclasses.replace(
        TINY_TEST, num_kv_heads=2, attention_impl="sparse",
        sparse_pattern="fixed", sparse_block=8,
        sparse_num_local_blocks=4, sparse_num_global_blocks=1)
    dense = dataclasses.replace(TINY_TEST, num_kv_heads=2,
                                attention_impl="reference")
    m_sparse, m_dense = CausalLM(wide), CausalLM(dense)
    params = m_sparse.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(rng.integers(0, 256, size=(2, 32)))
    np.testing.assert_allclose(
        np.asarray(m_sparse.apply(params, tokens)),
        np.asarray(m_dense.apply(params, tokens)), rtol=2e-4, atol=2e-5)


def test_gqa_dense_layout_matches_reference():
    """KH < H: the (KH, group) factorization equals grouped dense
    attention (fp32 softmax, no KV repeat)."""
    from deepspeed_tpu.models.transformer import attention_reference

    rng = np.random.default_rng(9)
    B, H, KH, T, D = 2, 8, 2, 64, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KH, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KH, T, D)).astype(np.float32))
    layout = np.tril(DenseSparsityConfig(num_heads=H, block=16)
                     .make_layout(T))
    out = sparse_attention(q, k, v, layout, block=16, causal=True)
    # attention_reference uses [B, T, H, D]
    ref = attention_reference(q.transpose(0, 2, 1, 3),
                              k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3), causal=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref.transpose(0, 2, 1, 3)),
                               rtol=2e-4, atol=2e-5)


def test_gqa_sparse_layout_matches_repeat_kv():
    """Genuinely sparse layout + GQA: matches repeat-KV dense reference
    through the padded gather and the (KH, group) masks."""
    rng = np.random.default_rng(10)
    B, H, KH, T, D = 2, 8, 2, 64, 8
    G = H // KH
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KH, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KH, T, D)).astype(np.float32))
    layout = FixedSparsityConfig(num_heads=H, block=8, num_local_blocks=2,
                                 num_global_blocks=1,
                                 attention="unidirectional").make_layout(T)
    mask = np.ones((B, T), dtype=bool)
    mask[:, 56:] = False
    out = sparse_attention(q, k, v, layout, block=8, causal=True,
                           key_padding_mask=jnp.asarray(mask))
    k_rep = jnp.repeat(k, G, axis=1)
    v_rep = jnp.repeat(v, G, axis=1)
    ref = sparse_attention(q, k_rep, v_rep, layout, block=8, causal=True,
                           key_padding_mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_gqa_rejects_mismatched_group_layouts():
    rng = np.random.default_rng(11)
    B, H, KH, T, D = 1, 4, 2, 32, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KH, T, D)).astype(np.float32))
    layout = DenseSparsityConfig(num_heads=H, block=8).make_layout(T)
    layout = np.array(layout)
    layout[1, 0, 2] = False     # head 1 differs from head 0 (same group)
    with pytest.raises(ValueError, match="identical layouts"):
        sparse_attention(q, k, k, layout, block=8)
