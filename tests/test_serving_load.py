"""Deterministic serving load test on the CPU mesh (ISSUE 1 acceptance):
mixed priorities/deadlines through the full stack, an over-capacity burst
that sheds with Rejected (bounded queue), cancellation that returns KV
blocks, replica fault degradation, and registry-sourced telemetry — the
same numbers bench.py's serving phase reports."""

import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving import (Priority, Rejected, RequestState,
                                   ServingConfig, ServingFrontend)

VOCAB = 128


def tiny_engine(kv_blocks=64, max_seqs=4):
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=2,
                            max_seq_len=128, norm="rmsnorm",
                            activation="silu", position="rope")
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=kv_blocks, kv_block_size=8,
        max_tracked_sequences=16)
    return InferenceEngineV2(CausalLM(cfg), config=vcfg)


@pytest.fixture
def frontend():
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=8))
    yield fe
    fe.shutdown(drain=False, timeout=5)


def prompts(n, rng, lo=8, hi=32):
    return [rng.integers(0, VOCAB, size=int(l)).tolist()
            for l in rng.integers(lo, hi, size=n)]


def test_requests_complete_and_stream(frontend):
    rng = np.random.default_rng(0)
    handles = [frontend.submit(p, max_new_tokens=6)
               for p in prompts(3, rng)]
    assert frontend.wait_all(handles, timeout=120)
    for h in handles:
        assert h.state == RequestState.FINISHED
        assert h.finish_reason == "length"
        toks = [ev.token for ev in h.drain()]
        assert len(toks) == 6
        assert all(0 <= t < VOCAB for t in toks)


def test_streaming_iterator_terminates(frontend):
    rng = np.random.default_rng(1)
    h = frontend.submit(prompts(1, rng)[0], max_new_tokens=5)
    seen = [ev.index for ev in h.stream(timeout=120)]
    assert seen == list(range(5))
    assert h.state == RequestState.FINISHED


def test_overcapacity_burst_sheds_and_admitted_complete(frontend):
    """The acceptance scenario: a burst far beyond queue+engine capacity
    is shed with Rejected("overloaded") — no unbounded queue growth — and
    every admitted request still completes."""
    rng = np.random.default_rng(2)
    handles, rejected = [], 0
    for p in prompts(40, rng):
        try:
            handles.append(frontend.submit(p, max_new_tokens=4))
        except Rejected as e:
            assert e.reason == "overloaded"
            rejected += 1
    assert rejected > 0, "burst was not over capacity"
    assert len(frontend.admission) <= frontend.config.max_queue_depth
    assert frontend.wait_all(handles, timeout=300)
    snap = frontend.metrics_snapshot()
    assert snap["requests_shed"] == rejected
    assert snap["requests_completed"] == len(handles)
    assert snap["shed_rate"] == pytest.approx(rejected / 40)
    # histograms actually populated by the load
    assert snap["ttft_s"]["count"] == len(handles)
    assert snap["ttft_s"]["p95"] >= snap["ttft_s"]["p50"] > 0
    assert snap["queue_wait_s"]["count"] >= len(handles)


def test_mixed_priorities_order_under_backlog():
    """Backlog beyond the replica's concurrency slots stays in the
    admission queue, where HIGH jumps ahead of already-queued LOW."""
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=32))
    try:
        rng = np.random.default_rng(3)
        # more LOWs than the replica has slots (max_ragged_sequence_count
        # = 4): the excess queues, and HIGHs submitted later overtake it
        lows = [fe.submit(p, max_new_tokens=8, priority=Priority.LOW)
                for p in prompts(8, rng)]
        highs = [fe.submit(p, max_new_tokens=8, priority=Priority.HIGH)
                 for p in prompts(3, rng)]
        assert fe.wait_all(lows + highs, timeout=300)
        first_high = min(h._req.first_token_t for h in highs)
        last_low = max(h._req.first_token_t for h in lows)
        assert first_high < last_low, (
            "HIGH priority should reach the engine before the last LOW")
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_cancel_frees_kv_blocks(frontend):
    rng = np.random.default_rng(4)
    engine = frontend.router.replicas[0].engine
    free0 = engine.free_blocks
    h = frontend.submit(prompts(1, rng, lo=30, hi=32)[0], max_new_tokens=90)
    # wait until it actually holds KV blocks
    deadline = time.monotonic() + 60
    while engine.free_blocks == free0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert engine.free_blocks < free0, "request never took KV blocks"
    h.cancel()
    assert h._req.wait(60)
    assert h.state == RequestState.CANCELLED
    assert h.finish_reason == "cancelled"
    # blocks back in the pool promptly (not at would-be completion time)
    deadline = time.monotonic() + 10
    while engine.free_blocks != free0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert engine.free_blocks == free0
    assert frontend.metrics_snapshot()["requests_cancelled"] == 1


def test_deadline_expiry_accounting(frontend):
    rng = np.random.default_rng(5)
    h = frontend.submit(prompts(1, rng, lo=30, hi=32)[0],
                        max_new_tokens=90, deadline_ms=120.0)
    assert h._req.wait(60)
    assert h.state == RequestState.EXPIRED
    assert h.finish_reason == "deadline"
    snap = frontend.metrics_snapshot()
    assert snap["requests_expired"] == 1
    # expiry released the sequence: all KV blocks return
    engine = frontend.router.replicas[0].engine
    deadline = time.monotonic() + 10
    while engine.free_blocks != engine.config.kv_blocks \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert engine.free_blocks == engine.config.kv_blocks


def test_two_replicas_share_load():
    engines = [tiny_engine(), tiny_engine()]
    fe = ServingFrontend(engines, ServingConfig(max_queue_depth=32))
    try:
        rng = np.random.default_rng(6)
        handles = [fe.submit(p, max_new_tokens=4)
                   for p in prompts(8, rng)]
        assert fe.wait_all(handles, timeout=300)
        used = {h._req.replica_id for h in handles}
        assert used == {0, 1}, f"router used only replicas {used}"
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_dead_replica_degrades_not_fails():
    """Kill one replica's engine mid-service: its requests FAIL, the
    other replica keeps serving, new work routes around the corpse."""
    engines = [tiny_engine(), tiny_engine()]
    fe = ServingFrontend(engines, ServingConfig(max_queue_depth=32))
    try:
        from deepspeed_tpu.serving import ReplicaState

        rng = np.random.default_rng(7)
        fe.router.replicas[0].state = ReplicaState.DEAD
        handles = [fe.submit(p, max_new_tokens=3)
                   for p in prompts(4, rng)]
        assert fe.wait_all(handles, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in handles)
        assert all(h._req.replica_id == 1 for h in handles)
        assert fe.metrics_snapshot()["replicas_healthy"] == 1
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_shutdown_drain_completes_inflight():
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=8))
    rng = np.random.default_rng(8)
    handles = [fe.submit(p, max_new_tokens=3) for p in prompts(2, rng)]
    fe.shutdown(drain=True, timeout=120)
    assert all(h.state == RequestState.FINISHED for h in handles)
    with pytest.raises(Rejected) as ei:
        fe.submit(prompts(1, rng)[0])
    assert ei.value.reason == "draining"


def test_bench_frontend_metrics_shape():
    """bench.py's serving phase consumes exactly these registry keys."""
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=4))
    try:
        rng = np.random.default_rng(9)
        handles = []
        for p in prompts(10, rng):
            try:
                handles.append(fe.submit(p, max_new_tokens=2))
            except Rejected:
                pass
        fe.wait_all(handles, timeout=300)
        snap = fe.metrics_snapshot()
        for key in ("requests_submitted", "requests_completed",
                    "requests_shed", "tokens_generated", "shed_rate"):
            assert key in snap
        assert {"p50", "p95", "count"} <= set(snap["ttft_s"])
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_cancel_queued_request_frees_depth_slot():
    """Cancelling a request still waiting in the admission queue must
    terminate it immediately and free its depth slot — not leave a
    phantom entry until it would reach the heap top."""
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=8))
    try:
        rng = np.random.default_rng(10)
        # saturate the replica's 4 slots; wait until all are dispatched
        busy = [fe.submit(p, max_new_tokens=40)
                for p in prompts(4, rng, lo=24, hi=32)]
        deadline = time.monotonic() + 30
        while len(fe.admission) and time.monotonic() < deadline:
            time.sleep(0.01)
        queued = [fe.submit(p, max_new_tokens=2) for p in prompts(2, rng)]
        depth = len(fe.admission)
        assert depth >= 1, "nothing queued; can't test cancel"
        victim = queued[0]
        victim.cancel()
        assert victim._req.wait(1.0), "queued cancel was not immediate"
        assert victim.state == RequestState.CANCELLED
        assert len(fe.admission) == depth - 1   # slot freed eagerly
        assert fe.wait_all(busy + queued[1:], timeout=300)
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_forced_shutdown_terminates_inflight():
    """shutdown(drain=False) with work in flight: every handle still
    reaches a terminal state (FAILED/REJECTED), no stream hangs."""
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=8))
    rng = np.random.default_rng(11)
    handles = [fe.submit(p, max_new_tokens=60)
               for p in prompts(6, rng, lo=24, hi=32)]
    fe.shutdown(drain=False, timeout=5)
    assert fe.wait_all(handles, timeout=30), (
        "forced shutdown left requests without a terminal state")
    assert all(h.state != RequestState.QUEUED and
               h.state != RequestState.RUNNING for h in handles)


def test_from_engine_factory_and_default_priority():
    """ServingConfig.num_replicas and default_priority are consumed: the
    factory path builds the fleet, and submit() without a priority uses
    the configured default."""
    fe = ServingFrontend.from_engine_factory(
        lambda i: tiny_engine(),
        ServingConfig(num_replicas=2, default_priority=Priority.HIGH,
                      max_queue_depth=8))
    try:
        assert len(fe.router.replicas) == 2
        rng = np.random.default_rng(12)
        h = fe.submit(prompts(1, rng)[0], max_new_tokens=2)
        assert h._req.priority == Priority.HIGH
        assert fe.wait_all([h], timeout=120)
    finally:
        fe.shutdown(drain=False, timeout=5)
