"""SLO observability suite (ISSUE 9): windowed metrics, burn-rate
alerting, the ops journal, and the health report
(docs/OBSERVABILITY.md "SLOs and burn-rate alerts").

Unit layers (no engines): histogram snapshot consistency under racing
``observe`` threads (the window-math-never-negative contract), the
windowed-metrics delta ring on a fake clock, journal schema/bounds/
ordering, and the AlertEngine state machine (fire needs evidence in
both windows; resolve needs evidence too — a data-less window must not
flap a firing alert). E2E layers (tiny CPU engines): a frontend whose
injected latency fault fires and resolves the interactive alert, the
health report's merged shape, and the training supervisor's journal.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.serving.metrics import (DEFAULT_LATENCY_BUCKETS,
                                           Histogram, MetricsRegistry,
                                           serving_metrics)
from deepspeed_tpu.telemetry import (AlertEngine, FlightRecorder,
                                     OpsJournal, SLOConfig, Tracer,
                                     WindowedMetrics, validate_events)

VOCAB = 128

_model = None
_params = None


def tiny_engine(i=0):
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    global _model, _params
    if _model is None:
        _model = CausalLM(TransformerConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, max_seq_len=128, norm="rmsnorm",
            activation="silu", position="rope"))
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=4,
        max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
        max_tracked_sequences=16)
    eng = InferenceEngineV2(_model, params=_params, config=vcfg)
    _params = eng.params
    return eng


def prompts(n, seed, lo=8, hi=20):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(l)).tolist()
            for l in rng.integers(lo, hi, size=n)]


# ------------------------------------------------- histogram consistency
class TestHistogramConsistency:
    def test_racing_observes_never_negative_deltas(self):
        """Satellite regression: two bucket snapshots taken around
        concurrent observes must have non-negative, mutually-consistent
        deltas (count delta == sum of bucket deltas; sum delta covers
        exactly the counted observations)."""
        h = Histogram((0.1, 1.0, 10.0))
        stop = threading.Event()

        def pound():
            while not stop.is_set():
                h.observe(0.05)
                h.observe(5.0)

        threads = [threading.Thread(target=pound) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            prev = h.buckets_snapshot()
            for _ in range(300):
                cur = h.buckets_snapshot()
                d_counts = [a - b for a, b in zip(cur[1], prev[1])]
                assert all(d >= 0 for d in d_counts), d_counts
                d_count = cur[3] - prev[3]
                d_sum = cur[2] - prev[2]
                assert d_count == sum(d_counts)
                assert d_count >= 0 and d_sum >= 0.0
                # every observation is 0.05 or 5.0: the sum delta must
                # equal the per-bucket composition exactly
                assert d_sum == pytest.approx(
                    d_counts[0] * 0.05 + d_counts[2] * 5.0)
                prev = cur
        finally:
            stop.set()
            for t in threads:
                t.join()

    def test_snapshot_internally_consistent_under_races(self):
        """Histogram.snapshot derives count/sum/mean/percentiles from ONE
        locked read — count always equals the percentile sample size."""
        h = Histogram((0.1, 1.0))
        stop = threading.Event()

        def pound():
            while not stop.is_set():
                h.observe(0.05)

        t = threading.Thread(target=pound)
        t.start()
        try:
            for _ in range(200):
                s = h.snapshot()
                if s["count"]:
                    assert s["sum"] == pytest.approx(s["count"] * 0.05)
                    assert s["mean"] == pytest.approx(0.05)
        finally:
            stop.set()
            t.join()

    def test_percentile_from_matches_cumulative(self):
        h = Histogram(DEFAULT_LATENCY_BUCKETS)
        rng = np.random.default_rng(0)
        for v in rng.uniform(0.001, 5.0, size=500):
            h.observe(float(v))
        bounds, counts, _, _ = h.buckets_snapshot()
        for q in (50, 90, 95, 99):
            assert h.percentile(q) == Histogram.percentile_from(
                bounds, counts, q)


# ---------------------------------------------------- windowed metrics
class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestWindowedMetrics:
    def _setup(self):
        reg = MetricsRegistry("serving")
        clock = FakeClock()
        w = WindowedMetrics(reg, bucket_s=1.0, history_s=60.0, clock=clock)
        return reg, w, clock

    def test_window_percentile_sees_only_the_window(self):
        reg, w, clock = self._setup()
        h = reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS)
        w.tick()
        for _ in range(20):
            h.observe(0.01)
        clock.t = 10.0
        w.tick()
        for _ in range(20):
            h.observe(0.4)
        clock.t = 12.0
        w.tick()
        # short window: only the slow batch; long window: both
        assert w.window_percentile("ttft_s", 95, 3.0) > 0.25
        assert w.window_percentile("ttft_s", 50, 100.0) < 0.1
        assert w.window_count("ttft_s", 3.0) == 20
        assert w.window_count("ttft_s", 100.0) == 40

    def test_window_agrees_with_cumulative_over_full_history(self):
        reg, w, clock = self._setup()
        h = reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS)
        w.tick()
        rng = np.random.default_rng(1)
        for v in rng.uniform(0.001, 2.0, size=300):
            h.observe(float(v))
        clock.t = 5.0
        w.tick()
        for q in (50, 95, 99):
            assert w.window_percentile("ttft_s", q, 1e9) == \
                h.percentile(q)

    def test_window_rate_and_delta(self):
        reg, w, clock = self._setup()
        c = reg.counter("tokens_generated")
        w.tick()
        c.inc(100)
        clock.t = 4.0
        w.tick()
        assert w.window_delta("tokens_generated", 10.0) == 100
        assert w.window_rate("tokens_generated", 10.0) == pytest.approx(25.0)

    def test_fraction_over_threshold(self):
        reg, w, clock = self._setup()
        h = reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS)
        w.tick()
        for _ in range(30):
            h.observe(0.01)
        for _ in range(10):
            h.observe(0.4)
        clock.t = 1.0
        w.tick()
        assert w.window_fraction_over("ttft_s", 0.1, 10.0) == \
            pytest.approx(0.25)
        # threshold beyond the largest bound: only +Inf overflow is over
        assert w.window_fraction_over("ttft_s", 1e6, 10.0) == 0.0

    def test_no_data_reads_none_not_zero(self):
        reg, w, clock = self._setup()
        reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS)
        assert w.window_percentile("ttft_s", 95, 10.0) is None
        w.tick()
        clock.t = 1.0
        w.tick()
        assert w.window_percentile("ttft_s", 95, 10.0) is None
        assert w.window_fraction_over("ttft_s", 0.1, 10.0) is None

    def test_reset_histogram_clamps_never_negative(self):
        reg, w, clock = self._setup()
        h = reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS)
        for _ in range(50):
            h.observe(0.01)
        w.tick()
        # re-declare with fresh counts (reset=True): deltas vs the old
        # baseline would be negative — must clamp to "window restarts"
        h2 = reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS, reset=True)
        h2.observe(0.01)
        clock.t = 1.0
        w.tick()
        d = w.window_hist("ttft_s", 10.0)
        assert d[3] >= 0 and all(c >= 0 for c in d[1]) and d[2] >= 0.0

    def test_stalled_ticks_read_no_data_not_stale(self):
        """If ticks stall longer than the window, there is no baseline
        inside it: the answer is None (no data), NOT a silently
        over-spanned window that smuggles a long-cleared incident back
        into a 'fast' burn rate."""
        reg, w, clock = self._setup()
        h = reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS)
        w.tick()
        for _ in range(10):
            h.observe(0.5)                  # the incident
        clock.t = 1.0
        w.tick()
        clock.t = 50.0                      # ticker stalled 49s
        w.tick()
        assert w.window_hist("ttft_s", 2.0) is None
        assert w.window_percentile("ttft_s", 95, 2.0) is None
        # the full-history window still answers
        assert w.window_count("ttft_s", 1e9) == 10

    def test_ring_is_bounded(self):
        reg, w, clock = self._setup()
        for i in range(500):
            clock.t = float(i)
            w.tick()
        assert len(w) <= w.max_snapshots

    def test_fast_tickers_refresh_head_not_history(self):
        """A dashboard polling tick() at 10x the cadence must not evict
        old snapshots (shrinking the slow window): faster-than-cadence
        ticks replace the ring head, persistent entries stay ~bucket_s
        apart, and the newest snapshot is still the freshest data."""
        reg, w, clock = self._setup()     # bucket_s = 1.0
        h = reg.histogram("ttft_s", DEFAULT_LATENCY_BUCKETS)
        w.tick()
        for i in range(1, 600):           # 60s of 10 Hz ticks
            clock.t = i * 0.1
            if i == 595:
                h.observe(0.01)           # lands just before the head
            w.tick()
        # ~60s span needs ~60-120 entries, nowhere near 600
        assert len(w) <= 125
        # old history survived AND the head saw the last observation
        assert w.window_count("ttft_s", 1e9) == 1
        assert w.window_count("ttft_s", 1.0) == 1

    def test_out_of_order_tick_dropped(self):
        reg, w, clock = self._setup()
        w.tick(5.0)
        w.tick(8.0)
        w.tick(6.0)                       # racing ticker lost the race
        pair = w._window_pair(100.0)
        assert pair[1]["t"] == 8.0 and len(w) == 2


# ------------------------------------------------------------ journal
class TestOpsJournal:
    def test_emit_and_validate(self):
        j = OpsJournal(capacity=16)
        j.emit("replica_restart", replica=0, attempt=1, recovery_s=0.5)
        j.emit("brownout_enter", healthy_fraction=0.4)
        assert validate_events(j.events()) == []
        assert [e["kind"] for e in j.events()] == ["replica_restart",
                                                   "brownout_enter"]

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown journal event"):
            OpsJournal().emit("meteor_strike", where="everywhere")

    def test_missing_required_field_raises(self):
        with pytest.raises(ValueError, match="missing required"):
            OpsJournal().emit("replica_restart", replica=0)

    def test_unserializable_detail_raises(self):
        with pytest.raises(ValueError, match="JSON-serializable"):
            OpsJournal().emit("brownout_enter",
                              healthy_fraction=object())

    def test_bounded_capacity_and_total(self):
        j = OpsJournal(capacity=5)
        for i in range(20):
            j.emit("train_wedge", step=i)
        assert len(j) == 5
        assert j.total_emitted == 20
        assert [e["detail"]["step"] for e in j.events()] == list(range(15, 20))

    def test_seq_and_timestamps_monotonic(self):
        j = OpsJournal(capacity=64)
        for i in range(30):
            j.emit("checkpoint_saved", step=i, urgent=False)
        evs = j.events()
        assert validate_events(evs) == []
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs)

    def test_filtering_and_count(self):
        j = OpsJournal()
        j.emit("train_wedge", step=1)
        j.emit("checkpoint_saved", step=1, urgent=False)
        j.emit("train_wedge", step=2)
        assert j.count("train_wedge") == 2
        assert len(j.events(kinds=("checkpoint_saved",))) == 1
        assert len(j.events(limit=1)) == 1

    def test_jsonl_sink_is_byte_capped(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        j = OpsJournal(capacity=1000, path=path, max_file_bytes=600)
        for i in range(50):
            j.emit("train_wedge", step=i)
        size = os.path.getsize(path)
        assert size <= 600
        # the in-memory ring kept everything; the sink just stopped
        assert len(j) == 50
        lines = [json.loads(l) for l in open(path)]
        assert all(l["kind"] == "train_wedge" for l in lines)

    def test_jsonl_sink_seq_ordered_under_concurrent_emits(self, tmp_path):
        """The durable sink must pass validate_events during exactly the
        multi-threaded incidents it exists to capture: lines land in seq
        order even with racing emitters."""
        path = str(tmp_path / "j.jsonl")
        j = OpsJournal(capacity=4096, path=path, max_file_bytes=10**7)

        def emit_many():
            for _ in range(200):
                j.emit("train_wedge", step=1)

        threads = [threading.Thread(target=emit_many) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lines = [json.loads(l) for l in open(path)]
        seqs = [l["seq"] for l in lines]
        assert seqs == list(range(1, 801))
        assert validate_events(lines) == []

    def test_dump_writes_ring_as_jsonl(self, tmp_path):
        j = OpsJournal()
        j.emit("brownout_enter", healthy_fraction=0.3)
        path = str(tmp_path / "dump.jsonl")
        assert j.dump(path) == 1
        assert json.loads(open(path).read())["kind"] == "brownout_enter"

    def test_render_text(self):
        j = OpsJournal()
        j.emit("replica_parked", replica=3, crashes_in_window=5)
        text = j.render_text()
        assert "replica_parked" in text and "replica=3" in text


# --------------------------------------------------------- alert engine
def make_alert_engine(classes=None, recorder=None, **over):
    reg = serving_metrics()
    clock = FakeClock()
    cfg = SLOConfig(enabled=True,
                    classes=classes or {"interactive":
                                        {"ttft_p95_ms": 100.0}},
                    fast_window_s=2.0, slow_window_s=6.0,
                    burn_rate_threshold=4.0, min_window_count=2,
                    eval_interval_s=0.0, **over)
    w = WindowedMetrics(reg, bucket_s=1.0, history_s=120.0, clock=clock)
    j = OpsJournal()
    eng = AlertEngine(cfg, w, metrics=reg, journal=j, recorder=recorder,
                      clock=clock)
    return reg, w, j, eng, clock


class TestAlertEngine:
    def test_rules_built_and_gauges_predeclared(self):
        reg, _, _, eng, _ = make_alert_engine(
            classes={"interactive": {"ttft_p95_ms": 100.0,
                                     "tpot_p95_ms": 20.0,
                                     "availability": 0.999}})
        names = {r.name for r in eng.rules}
        assert names == {"slo_ttft_interactive", "slo_tpot_interactive",
                         "slo_availability_interactive"}
        gauges = reg.names()["gauges"]
        assert "alerts_firing" in gauges
        for n in names:
            assert f"alert_firing_{n}" in gauges

    def test_fires_on_both_windows_then_resolves_on_evidence(self):
        reg, w, j, eng, clock = make_alert_engine()
        h = reg.histogram("ttft_s_class_interactive")
        w.tick()
        assert eng.evaluate(0.0) == []          # no data: no transitions
        for _ in range(10):
            h.observe(0.5)                      # all over the 100ms target
        clock.t = 1.0
        w.tick()
        trs = eng.evaluate(1.0)
        assert [t["transition"] for t in trs] == ["firing"]
        assert eng.firing() == ["slo_ttft_interactive"]
        assert reg.gauge("alerts_firing").value == 1.0
        assert reg.gauge("alert_firing_slo_ttft_interactive").value == 1.0
        # recovery: fresh fast traffic, the bad batch ages out of the
        # fast window while still inside the slow one
        for t_new in (2.0, 3.0, 4.0):
            clock.t = t_new
            for _ in range(10):
                h.observe(0.001)
            w.tick()
        trs = eng.evaluate(4.0)
        assert [t["transition"] for t in trs] == ["resolved"]
        assert eng.firing() == []
        assert reg.gauge("alerts_firing").value == 0.0
        kinds = [e["kind"] for e in j.events()]
        assert kinds == ["alert_firing", "alert_resolved"]
        assert validate_events(j.events()) == []

    def test_no_evidence_neither_fires_nor_resolves(self):
        reg, w, j, eng, clock = make_alert_engine()
        h = reg.histogram("ttft_s_class_interactive")
        w.tick()
        h.observe(9.0)                          # ONE terrible request
        clock.t = 1.0
        w.tick()
        assert eng.evaluate(1.0) == []          # below min_window_count
        # now a real breach...
        for _ in range(10):
            h.observe(9.0)
        clock.t = 2.0
        w.tick()
        assert [t["transition"] for t in eng.evaluate(2.0)] == ["firing"]
        # ...then total silence: empty fast windows must NOT flap it
        for t_new in (5.0, 9.0, 20.0):
            clock.t = t_new
            w.tick()
            assert eng.evaluate(t_new) == []
        assert eng.firing() == ["slo_ttft_interactive"]

    def test_slow_window_guards_against_blips(self):
        """A burst that breaches the fast window but not the slow one
        (diluted by history) must not fire."""
        reg, w, j, eng, clock = make_alert_engine()
        h = reg.histogram("ttft_s_class_interactive")
        w.tick()
        for t_new in (1.0, 2.0, 3.0, 4.0):      # 4s of good history
            clock.t = t_new
            for _ in range(50):
                h.observe(0.001)
            w.tick()
        for _ in range(30):                     # short bad blip
            h.observe(0.5)
        clock.t = 5.0
        w.tick()
        trs = eng.evaluate(5.0)
        st = eng.status()["slo_ttft_interactive"]
        assert st["burn_fast"] > 4.0            # fast window IS breached
        assert st["burn_slow"] < 4.0            # slow one absorbs the blip
        assert trs == [] and eng.firing() == []

    def test_availability_rule(self):
        reg, w, j, eng, clock = make_alert_engine(
            classes={"batch": {"availability": 0.99}})
        sub = reg.counter("requests_submitted_class_batch")
        shed = reg.counter("requests_shed_class_batch")
        w.tick()
        sub.inc(20)
        shed.inc(10)                            # 50% shed vs 1% budget
        clock.t = 1.0
        w.tick()
        trs = eng.evaluate(1.0)
        assert [t["transition"] for t in trs] == ["firing"]
        assert eng.firing() == ["slo_availability_batch"]

    def test_status_reports_budget_spend(self):
        reg, w, j, eng, clock = make_alert_engine()
        h = reg.histogram("ttft_s_class_interactive")
        for _ in range(95):
            h.observe(0.001)
        for _ in range(5):
            h.observe(0.5)
        st = eng.status()["slo_ttft_interactive"]
        # 5% bad on a 5% budget: the whole budget is spent, exactly
        assert st["budget_spent_frac"] == pytest.approx(1.0)
        assert st["target_ms"] == 100.0

    def test_new_firing_dumps_flight_recorder_rate_limited(self, tmp_path):
        tracer = Tracer(enabled=True)
        rec = FlightRecorder(tracer, dump_dir=str(tmp_path),
                             max_error_dumps=1, error_dump_window_s=3600.0)
        reg, w, j, eng, clock = make_alert_engine(recorder=rec)
        h = reg.histogram("ttft_s_class_interactive")
        w.tick()

        def breach_then_recover(t0):
            clock.t = t0 - 1.0
            w.tick()              # keep tick cadence inside the window
            clock.t = t0
            for _ in range(10):
                h.observe(0.5)
            w.tick()
            eng.evaluate(clock.t)
            for dt in (2.0, 4.0):
                clock.t = t0 + dt
                for _ in range(10):
                    h.observe(0.001)
                w.tick()
            eng.evaluate(clock.t)

        breach_then_recover(1.0)
        breach_then_recover(10.0)
        states = eng.status()["slo_ttft_interactive"]
        assert states["fire_count"] == 2
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flightrec_")]
        assert len(dumps) == 1                  # second firing rate-limited
        assert "alert_slo_ttft_interactive" in dumps[0]


# ---------------------------------------------- registry pre-declaration
class TestRegistryPredeclaration:
    def test_custom_classes_expose_zero_series_before_traffic(self):
        reg = serving_metrics(["interactive", "batch", "bulk_eval"])
        text = reg.render_prometheus()
        for cls in ("interactive", "batch", "bulk_eval"):
            assert f"serving_requests_shed_class_{cls} 0" in text
            assert f"serving_requests_submitted_class_{cls} 0" in text
            assert f"serving_queue_depth_class_{cls} 0" in text
            assert f"serving_ttft_s_class_{cls}_count 0" in text
            assert f"serving_tpot_s_class_{cls}_count 0" in text

    def test_stock_registry_has_alerts_firing(self):
        assert "alerts_firing" in serving_metrics().names()["gauges"]

    def test_frontend_declares_configured_classes(self):
        from deepspeed_tpu.serving import ServingConfig, ServingFrontend

        fe = ServingFrontend([tiny_engine()], ServingConfig(
            max_queue_depth=8,
            classes={"realtime": {"priority": 0, "deadline_ms": 500.0}}))
        try:
            assert "ttft_s_class_realtime" in \
                fe.metrics.names()["histograms"]
            assert "serving_requests_shed_class_realtime 0" in \
                fe.render_prometheus()
        finally:
            fe.shutdown(drain=False, timeout=5)


def _doc_metric_table():
    """Parse docs/OBSERVABILITY.md's metric-name reference table into
    {name: kind} (the satellite audit surface)."""
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "OBSERVABILITY.md")
    doc = open(path).read()
    assert "## Metric name reference" in doc, \
        "docs/OBSERVABILITY.md lost its '## Metric name reference' section"
    section = doc.split("## Metric name reference", 1)[1]
    section = section.split("\n## ", 1)[0]
    table = {}
    for line in section.splitlines():
        line = line.strip()
        if not line.startswith("| `"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        name = cells[0].strip("`")
        kind = cells[1]
        if "<" in name:          # templated rows (per-rule gauges etc.)
            continue
        table[name] = kind
    return table


class TestMetricNameAudit:
    def test_docs_and_registry_agree_both_ways(self):
        """Every metric a fresh registry declares is documented, and
        every documented (non-templated) name exists in a fresh registry
        — docs/OBSERVABILITY.md cannot drift from the code."""
        doc = _doc_metric_table()
        reg = serving_metrics().names()
        actual = {}
        for kind, names in (("counter", reg["counters"]),
                            ("gauge", reg["gauges"]),
                            ("histogram", reg["histograms"])):
            for n in names:
                actual[n] = kind
        missing_in_docs = sorted(set(actual) - set(doc))
        assert not missing_in_docs, \
            f"registry metrics undocumented in OBSERVABILITY.md: " \
            f"{missing_in_docs}"
        ghosts = sorted(set(doc) - set(actual))
        assert not ghosts, \
            f"OBSERVABILITY.md documents metrics no registry declares: " \
            f"{ghosts}"
        wrong_kind = {n: (doc[n], actual[n]) for n in doc
                      if doc[n] != actual[n]}
        assert not wrong_kind, f"kind mismatches: {wrong_kind}"


# --------------------------------------------------------- e2e serving
class TestServingE2E:
    def test_health_report_shape_with_everything_off(self):
        from deepspeed_tpu.serving import ServingConfig, ServingFrontend

        fe = ServingFrontend([tiny_engine()],
                             ServingConfig(max_queue_depth=16))
        try:
            hs = [fe.submit(p, max_new_tokens=3) for p in prompts(4, 0)]
            assert fe.wait_all(hs, timeout=120)
            rep = fe.health_report(window_s=30.0)
            assert rep["slo"] is None and rep["alerts_firing"] == []
            assert rep["counters"]["requests_completed"] == 4
            assert rep["replicas"][0]["state"] == "healthy"
            assert "interactive" in rep["queue"]["per_class"]
            assert rep["window"]["ttft_s"]["count"] >= 1
            text = fe.health_report_text(window_s=30.0)
            assert "serving health" in text and "submitted=4" in text
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_latency_fault_fires_and_resolves_alert(self):
        """The bench slo phase's core story as a tier-1 test: a
        slow_forward fault inflates interactive TTFT past the target,
        the burn-rate alert fires (gauge + journal), and once the fault
        clears and fresh traffic repopulates the fast window it
        resolves."""
        from deepspeed_tpu.serving import ServingConfig, ServingFrontend

        scfg = ServingConfig(
            max_queue_depth=32,
            slo={"enabled": True,
                 "classes": {"interactive": {"ttft_p95_ms": 50.0}},
                 "fast_window_s": 0.6, "slow_window_s": 1.5,
                 "window_bucket_s": 0.15, "eval_interval_s": 0.1,
                 "burn_rate_threshold": 4.0, "min_window_count": 2},
            faults={"enabled": True, "schedule": [
                {"kind": "slow_forward", "replica": 0, "at_put": 6,
                 "count": 12, "duration_s": 0.08}]})
        fe = ServingFrontend([tiny_engine()], scfg)
        try:
            ps = prompts(40, 3)
            # warmup compiles outside the fault window (at_put=6)
            fe.wait_all([fe.submit(ps[0], max_new_tokens=2)], timeout=120)
            fired = resolved = False
            deadline = time.monotonic() + 30.0
            i = 0
            while time.monotonic() < deadline and not (fired and resolved):
                h = fe.submit(ps[i % len(ps)], max_new_tokens=3,
                              request_class="interactive")
                h.result(timeout=60)
                i += 1
                fired = fired or fe.journal.count("alert_firing") > 0
                resolved = fired and fe.journal.count("alert_resolved") > 0
            assert fired, "injected latency never fired the alert"
            assert resolved, "alert never resolved after the fault cleared"
            assert fe.metrics.snapshot()["alerts_firing"] == 0.0
            evs = fe.journal.events(kinds=("alert_firing",
                                           "alert_resolved"))
            assert [e["kind"] for e in evs] == ["alert_firing",
                                                "alert_resolved"]
            assert validate_events(fe.journal.events()) == []
            rep = fe.health_report()
            assert rep["slo"]["slo_ttft_interactive"]["fire_count"] == 1
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_windowed_ring_fed_by_router_tick(self):
        from deepspeed_tpu.serving import ServingConfig, ServingFrontend

        fe = ServingFrontend([tiny_engine()], ServingConfig(
            max_queue_depth=16,
            slo={"enabled": False, "window_bucket_s": 0.05}))
        try:
            hs = [fe.submit(p, max_new_tokens=3) for p in prompts(3, 5)]
            assert fe.wait_all(hs, timeout=120)
            deadline = time.monotonic() + 10.0
            while len(fe.windowed) < 3 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert len(fe.windowed) >= 3, \
                "router tick never fed the windowed ring"
        finally:
            fe.shutdown(drain=False, timeout=5)


# -------------------------------------------------------- e2e training
class TestTrainingHealthReport:
    def _build(self, tmp_path, faults=None):
        import deepspeed_tpu
        import deepspeed_tpu.parallel.topology as topo
        from deepspeed_tpu.models import build_model

        topo.reset_topology()
        rng = np.random.default_rng(0)
        cfg = {
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": -1, "fsdp": 1},
            "steps_per_print": 10**9,
            "resilience": {"enabled": True, "save_dir": str(tmp_path),
                           "save_interval_steps": 2,
                           "restart_backoff_s": 0.01,
                           "restart_backoff_jitter": 0.0,
                           "watchdog_enabled": False,
                           "faults": faults or {"enabled": False}},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_model("tiny"), config=cfg,
            training_data={"input_ids": rng.integers(
                0, 256, size=(64, 33), dtype=np.int64)})
        return engine

    def test_journal_records_checkpoints_restarts_and_report(self, tmp_path):
        from deepspeed_tpu.runtime.resilience import TrainingSupervisor

        engine = self._build(tmp_path, faults={
            "enabled": True,
            "schedule": [{"kind": "crash", "at_step": 3}]})
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(5)
        assert r["status"] == "completed"
        assert sup.journal.count("train_restart") == 1
        assert sup.journal.count("checkpoint_saved") >= 2
        assert validate_events(sup.journal.events()) == []
        rep = sup.health_report()
        assert rep["global_step"] == 5
        assert rep["counters"]["train_restarts"] == 1
        assert any(e["kind"] == "train_restart" for e in rep["events"])
        text = sup.health_report_text()
        assert "training health" in text and "restarts=1" in text
