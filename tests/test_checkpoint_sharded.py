"""Multi-host-safe sharded + async checkpointing.

Mirrors the reference's per-rank zero-shard checkpoint layout tests
(tests/unit/checkpoint/): each process writes only the shards it owns,
nothing is gathered to one host, and async saves don't block the step
loop."""

import glob
import itertools
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.parallel.topology as topo
from deepspeed_tpu.models import build_model


def make_engine(stage=3, mesh=None):
    topo.reset_topology()
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh or {"data": -1, "fsdp": 2},
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                               config=config)
    return engine


def train(engine, steps, seed=0):
    rng = np.random.default_rng(seed)
    dp = engine.topology.get_data_parallel_world_size()
    batch = {"input_ids": rng.integers(0, 256, size=(2 * dp, 33),
                                       dtype=np.int64)}
    return [float(engine.train_batch(itertools.repeat(batch)))
            for _ in range(steps)]


def test_sharded_save_writes_per_shard_files(tmp_path):
    engine = make_engine()
    train(engine, 2)
    tag_dir = engine.save_checkpoint(str(tmp_path))
    shard_files = glob.glob(os.path.join(tag_dir, "params", "*.shard_*.npy"))
    assert shard_files, "stage-3 save produced no per-shard files"
    # a sharded leaf's shard files are strictly smaller than the full leaf
    wte = engine.state.params["embed"]["wte"]
    wte_shards = glob.glob(os.path.join(tag_dir, "params",
                                        "embed.wte.shard_*.npy"))
    assert wte_shards
    for f in wte_shards:
        assert np.load(f).size < wte.size


def test_no_full_gather_on_save(tmp_path, monkeypatch):
    """The save path must never device_get a sharded array whole (raises on
    non-fully-addressable arrays in real multi-host meshes)."""
    engine = make_engine()
    train(engine, 1)
    real_device_get = jax.device_get

    def guarded(x):
        if isinstance(x, jax.Array) and not x.is_fully_replicated:
            raise AssertionError("full-array device_get of a sharded leaf")
        return real_device_get(x)

    monkeypatch.setattr(jax, "device_get", guarded)
    engine.save_checkpoint(str(tmp_path))


def test_sharded_roundtrip_cross_mesh(tmp_path):
    engine = make_engine(mesh={"data": -1, "fsdp": 2})
    losses_a = train(engine, 3)
    engine.save_checkpoint(str(tmp_path))
    ref_params = [np.asarray(l) for l in jax.tree.leaves(engine.state.params)]

    engine2 = make_engine(mesh={"data": -1, "fsdp": 4})
    engine2.load_checkpoint(str(tmp_path))
    for a, b in zip(ref_params, jax.tree.leaves(engine2.state.params)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)
    # identical forward trajectory after resume
    cont_a = train(engine, 2, seed=7)
    cont_b = train(engine2, 2, seed=7)
    np.testing.assert_allclose(cont_a, cont_b, rtol=1e-4, atol=1e-5)


def test_async_save_does_not_block_and_is_durable(tmp_path):
    engine = make_engine()
    train(engine, 2)
    snap = [np.asarray(l) for l in jax.tree.leaves(engine.state.params)]
    tag_dir = engine.save_checkpoint(str(tmp_path), async_save=True)
    # step loop continues while writes are in flight (donation-safe: shard
    # bytes were snapshot before save_checkpoint returned)
    train(engine, 2)
    engine.wait_pending_checkpoint()

    engine2 = make_engine()
    engine2.load_checkpoint(str(tmp_path))
    for a, b in zip(snap, jax.tree.leaves(engine2.state.params)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)
    assert os.path.basename(tag_dir).startswith("global_step")


def test_failed_save_leaves_previous_latest_loadable(tmp_path, monkeypatch):
    """Crash-safe commit marker: a save that fails mid-write (simulated
    np.save fault) must raise AND leave 'latest' pointing at the previous
    fully-written tag — a restart resumes from it as if the failed save
    never happened."""
    engine = make_engine()
    train(engine, 2)
    engine.save_checkpoint(str(tmp_path), tag="good")
    snap = [np.asarray(l) for l in jax.tree.leaves(engine.state.params)]

    real_save = np.save
    def exploding_save(fname, arr, *a, **kw):
        raise IOError(f"disk full writing {fname}")
    monkeypatch.setattr(np, "save", exploding_save)
    train(engine, 1)
    with pytest.raises(IOError):
        engine.save_checkpoint(str(tmp_path), tag="torn")
    monkeypatch.setattr(np, "save", real_save)

    latest = (tmp_path / "latest").read_text().strip()
    assert latest == "good", f"'latest' points at the failed tag {latest!r}"
    assert not list(tmp_path.glob("latest.tmp*")), "torn temp file leaked"
    engine2 = make_engine()
    engine2.load_checkpoint(str(tmp_path))       # resolves via 'latest'
    for a, b in zip(snap, jax.tree.leaves(engine2.state.params)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)


def test_failed_async_save_never_commits_latest(tmp_path, monkeypatch):
    """Async variant: shard-write errors surface on the join AND the
    pending commit closure is dropped — a LATER save's join must not
    publish the failed tag's 'latest' pointer."""
    engine = make_engine()
    train(engine, 2)
    engine.save_checkpoint(str(tmp_path), tag="good")

    real_save = np.save
    monkeypatch.setattr(np, "save",
                        lambda *a, **kw: (_ for _ in ()).throw(
                            IOError("injected write failure")))
    engine.save_checkpoint(str(tmp_path), tag="torn", async_save=True)
    with pytest.raises(IOError):
        engine.wait_pending_checkpoint()
    monkeypatch.setattr(np, "save", real_save)
    # a subsequent good save must not resurrect the failed commit
    engine.save_checkpoint(str(tmp_path), tag="good2")
    assert (tmp_path / "latest").read_text().strip() == "good2"


def test_checkpoint_writer_surfaces_ioerror_on_finalize():
    """A writer thread hitting a bad path collects the error and
    finalize() raises it (not silently dropped), with the worker thread
    joined — no thread leaks out of a failed save."""
    from deepspeed_tpu.runtime.checkpointing import CheckpointWriter

    w = CheckpointWriter()
    w.submit("/nonexistent-dir-xyz/leaf.npy", np.zeros(3))
    with pytest.raises(IOError, match="checkpoint writes failed"):
        w.finalize()
    assert not w._thread.is_alive(), "failed finalize leaked the worker"
    # a clean writer finalizes without error and also leaves no thread
    w2 = CheckpointWriter()
    w2.finalize()
    assert not w2._thread.is_alive()


def test_urgent_save_joins_async_and_publishes_fresh_latest(tmp_path):
    """SIGTERM-grace-window save (docs/TRAINING.md): an urgent save racing
    an in-flight async save joins it first, completes synchronously, and
    'latest' ends on the URGENT tag with a complete manifest — never torn,
    never stale. Wall time is measured onto engine.last_urgent_save_s."""
    import json

    engine = make_engine()
    train(engine, 2)
    engine.save_checkpoint(str(tmp_path), tag="async_tag", async_save=True)
    # race: urgent save while async writes may still be in flight
    engine.save_checkpoint(str(tmp_path), tag="urgent_tag", urgent=True)
    assert engine.last_urgent_save_s > 0       # measured wall-time bound
    assert (tmp_path / "latest").read_text().strip() == "urgent_tag"
    for tag in ("async_tag", "urgent_tag"):    # both fully committed
        manifest = json.loads((tmp_path / tag / "manifest.json").read_text())
        assert manifest["tag"] == tag
    engine2 = make_engine()
    engine2.load_checkpoint(str(tmp_path))     # resolves via 'latest'
    for a, b in zip(jax.tree.leaves(engine.state.params),
                    jax.tree.leaves(engine2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_urgent_save_survives_failed_async_join(tmp_path, monkeypatch):
    """A broken PREVIOUS async save must not abort the preemption save:
    the urgent path logs the join failure, drops the failed tag's commit,
    and still publishes its own complete checkpoint as 'latest'."""
    engine = make_engine()
    train(engine, 2)
    real_save = np.save

    def torn_only_save(fname, arr, *a, **kw):
        # fail ONLY the async tag's shard writes (path-selective, not
        # time-selective: the background writer may drain the queue at
        # any point relative to this test's statements)
        if "torn" in str(fname):
            raise IOError(f"injected write failure: {fname}")
        return real_save(fname, arr, *a, **kw)

    monkeypatch.setattr(np, "save", torn_only_save)
    engine.save_checkpoint(str(tmp_path), tag="torn", async_save=True)
    # non-urgent surfaces the error; urgent must survive it
    engine.save_checkpoint(str(tmp_path), tag="urgent_tag", urgent=True)
    assert (tmp_path / "latest").read_text().strip() == "urgent_tag"
    assert not (tmp_path / "torn" / "manifest.json").exists()
    engine2 = make_engine()
    engine2.load_checkpoint(str(tmp_path))
    for a, b in zip(jax.tree.leaves(engine.state.params),
                    jax.tree.leaves(engine2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_to_fp32_offline_reconstruction(tmp_path):
    """zero_to_fp32 CLI role: rebuild full fp32 weights from shard files
    with no engine/mesh (reference utils/zero_to_fp32.py)."""
    import subprocess
    import sys

    from deepspeed_tpu.utils.zero_to_fp32 import (
        get_fp32_state_dict_from_zero_checkpoint)

    engine = make_engine(stage=3)
    train(engine, 2)
    engine.save_checkpoint(str(tmp_path))
    expect = {".".join(str(getattr(p, "key", p)) for p in path): np.asarray(leaf)
              for path, leaf in jax.tree_util.tree_flatten_with_path(
                  engine.state.params)[0]}

    state = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
    assert set(state) == set(expect)
    for k in expect:
        np.testing.assert_allclose(state[k], expect[k], rtol=1e-6)

    out = tmp_path / "consolidated.npz"
    r = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.utils.zero_to_fp32",
         str(tmp_path), str(out)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    loaded = np.load(out)
    np.testing.assert_allclose(loaded[sorted(expect)[0]],
                               expect[sorted(expect)[0]], rtol=1e-6)


def test_sharded_roundtrip_tp_change(tmp_path):
    """Universal layout reshapes across TENSOR parallelism too: save on a
    tp=2 x fsdp=2 mesh, resume on pure dp (reference ds_to_universal's
    merge_tp_slices role — here a device_put with the new sharding)."""
    engine = make_engine(mesh={"data": -1, "fsdp": 2, "tensor": 2})
    train(engine, 2)
    engine.save_checkpoint(str(tmp_path))
    ref = [np.asarray(l) for l in jax.tree.leaves(engine.state.params)]

    engine2 = make_engine(mesh={"data": -1, "fsdp": 1, "tensor": 1})
    engine2.load_checkpoint(str(tmp_path))
    for a, b in zip(ref, jax.tree.leaves(engine2.state.params)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)
    losses = train(engine2, 2, seed=5)
    assert np.isfinite(losses).all()
