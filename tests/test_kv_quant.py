"""int8 KV-cache quantization (ISSUE 6): int8 pools + per-(layer, block,
kv-head) scale planes in DSStateManager, fused quantized write /
in-kernel dequantized read in PagedCausalLM, engine/serving config
plumbing, occupancy observability, and composition with every subsystem
that touches KV blocks (prefix cache, speculative trim, failover,
cancel). The quant-off engine must behave byte-for-byte like the
pre-quant engine; quant-on carries bounded-divergence + perplexity
gates (docs/SERVING.md "KV quantization")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.kv_quant import (blocks_for_budget,
                                                 kv_bytes_per_block,
                                                 validate_kv_quant)
from deepspeed_tpu.inference.v2.ragged import BlockedAllocator, DSStateManager
from deepspeed_tpu.inference.v2.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.inference.v2.spec import NGramProposer
from deepspeed_tpu.inference.v2.testing import (assert_greedy_parity,
                                                greedy_generate)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

VOCAB = 128
BS = 16          # kv block size used throughout


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=4,
                            max_seq_len=256, norm="rmsnorm",
                            activation="silu", position="rope")
    model = CausalLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def make_engine(model, params, quant=True, kv_blocks=64, max_seqs=8,
                qdtype="int8", **cfg_kw):
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=256, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=kv_blocks, kv_block_size=BS,
        max_tracked_sequences=64, kv_quant_enabled=quant,
        kv_quant_dtype=qdtype, **cfg_kw)
    return InferenceEngineV2(model, params=params, config=vcfg)


# the representation axis (ISSUE 13): the PR 6 suite runs for both the
# int8 pools and fp8_e4m3 on the reserved ``kv_quant.dtype`` surface —
# same scale machinery, different payload dtype
KV_DTYPES = ("int8", "fp8_e4m3")


def rand_prompt(rng, n):
    return rng.integers(0, VOCAB, size=n).tolist()


# ------------------------------------------------------------ state + bytes
@pytest.mark.parametrize("qdtype", KV_DTYPES)
def test_quantized_pools_and_scale_planes(model_and_params, qdtype):
    model, params = model_and_params
    eng = make_engine(model, params, quant=True, qdtype=qdtype)
    kv = eng.state_manager.kv_cache
    L, KH, D = model.cfg.num_layers, model.cfg.kv_heads, model.cfg.head_dim
    want = jnp.int8 if qdtype == "int8" else jnp.float8_e4m3fn
    assert kv["k"].dtype == want and kv["v"].dtype == want
    assert kv["k_scale"].shape == (L, 64, KH)
    assert kv["k_scale"].dtype == jnp.float32
    # quant-off: no scale planes at all (the forward branches on the
    # cache pytree, so absence IS the byte-identical historical program)
    off = make_engine(model, params, quant=False)
    assert set(off.state_manager.kv_cache) == {"k", "v"}


def test_bytes_per_block_and_budget(model_and_params):
    model, params = model_and_params
    cfg = model.cfg
    base = kv_bytes_per_block(cfg, BS, quant=False)
    i8 = kv_bytes_per_block(cfg, BS, quant=True)
    slab = cfg.num_layers * cfg.kv_heads * BS * cfg.head_dim
    assert base == 2 * slab * jnp.dtype(cfg.dtype).itemsize
    assert i8 == 2 * slab + 2 * cfg.num_layers * cfg.kv_heads * 4
    assert i8 < base
    # the headline claim: a fixed byte budget buys >= 1.5x the blocks
    budget = 32 * base
    assert blocks_for_budget(budget, cfg, BS, quant=True) >= 48
    eng = make_engine(model, params, quant=True)
    occ = eng.occupancy()
    assert occ["bytes_per_block"] == i8
    assert occ["bytes_total"] == 64 * i8


def test_validate_kv_quant_rejects_unknown():
    validate_kv_quant("int8", "block")
    validate_kv_quant("fp8_e4m3", "block")    # ISSUE 13: now real
    with pytest.raises(ValueError, match="dtype"):
        validate_kv_quant("fp8", "block")     # the short spelling is not
    with pytest.raises(ValueError, match="scale_granularity"):
        validate_kv_quant("int8", "tensor")


def test_allocator_occupancy_math():
    a = BlockedAllocator(8, bytes_per_block=100)
    a.allocate(3)
    occ = a.occupancy()
    assert occ == {"total_blocks": 8, "free_blocks": 5, "in_use_blocks": 3,
                   "bytes_per_block": 100, "bytes_in_use": 300,
                   "bytes_total": 800}


# ----------------------------------------------------- disabled byte-parity
def test_disabled_path_byte_identical(model_and_params):
    """kv_quant config present-but-disabled must produce the exact same
    logits as an engine that never heard of it."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    prompt = rand_prompt(rng, 30)
    eng_default = InferenceEngineV2(model, params=params,
                                    config=RaggedInferenceEngineConfig(
                                        max_ragged_batch_size=256,
                                        max_ragged_sequence_count=8,
                                        max_chunk_tokens=32, kv_blocks=64,
                                        kv_block_size=BS))
    eng_off = make_engine(model, params, quant=False)
    la = np.asarray(eng_default.put([1], [prompt]))
    lb = np.asarray(eng_off.put([1], [prompt]))
    np.testing.assert_array_equal(la, lb)


def test_disabled_greedy_stream_identical(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(1)
    prompts = [rand_prompt(rng, 25), rand_prompt(rng, 18)]
    g_default = greedy_generate(
        InferenceEngineV2(model, params=params,
                          config=RaggedInferenceEngineConfig(
                              max_ragged_batch_size=256,
                              max_ragged_sequence_count=8,
                              max_chunk_tokens=32, kv_blocks=64,
                              kv_block_size=BS)),
        prompts, uid_base=1, max_new_tokens=10)
    g_off = greedy_generate(make_engine(model, params, quant=False),
                            prompts, uid_base=1, max_new_tokens=10)
    assert_greedy_parity(g_default, g_off, label="kv_quant disabled")


# ------------------------------------------------- quality gates (quant on)
@pytest.mark.parametrize("qdtype", KV_DTYPES)
def test_bounded_divergence_and_logit_error(model_and_params, qdtype):
    model, params = model_and_params
    rng = np.random.default_rng(2)
    prompts = [rand_prompt(rng, 30) for _ in range(3)]
    g_off = greedy_generate(make_engine(model, params, quant=False),
                            prompts, uid_base=1, max_new_tokens=16)
    g_on = greedy_generate(make_engine(model, params, quant=True,
                                       qdtype=qdtype),
                           prompts, uid_base=1, max_new_tokens=16)
    fracs = []
    for a, b in zip(g_off, g_on):
        matched = next((i for i, (x, y) in enumerate(zip(a, b)) if x != y),
                       min(len(a), len(b)))
        fracs.append(matched / max(1, len(a)))
    # int8 with per-block scales: ~0.1% relative logit error on this
    # model — streams should mostly agree; gate loosely, report exactly
    assert np.mean(fracs) >= 0.5, f"divergence too large: {fracs}"
    # teacher-forced logits stay close
    p = prompts[0]
    la = np.asarray(make_engine(model, params, quant=False).put([9], [p]))
    lb = np.asarray(make_engine(model, params, quant=True,
                                qdtype=qdtype).put([9], [p]))
    rel = np.max(np.abs(la - lb)) / (np.max(np.abs(la)) + 1e-9)
    assert rel < 0.05, f"relative logit error {rel}"


@pytest.mark.parametrize("qdtype", KV_DTYPES)
def test_perplexity_delta_gate(model_and_params, qdtype):
    """Teacher-forced perplexity of the quantized engine within 5% of
    the unquantized engine (the bench kv_quant phase's gate, in
    miniature) — both the int8 and fp8_e4m3 representations."""
    model, params = model_and_params
    rng = np.random.default_rng(3)
    toks = rand_prompt(rng, 64)
    chunk = 16

    def nll(eng, uid):
        total, count = 0.0, 0
        for lo in range(0, len(toks), chunk):
            ch = toks[lo:lo + chunk]
            logits = np.asarray(eng.put([uid], [ch],
                                        verify_width=len(ch)))[0]
            for j in range(len(ch)):
                t = lo + j + 1
                if t >= len(toks):
                    break
                row = logits[j].astype(np.float64)
                lse = row.max() + np.log(np.exp(row - row.max()).sum())
                total += lse - row[toks[t]]
                count += 1
        return total / count

    ppl_off = np.exp(nll(make_engine(model, params, quant=False), 1))
    ppl_on = np.exp(nll(make_engine(model, params, quant=True,
                                    qdtype=qdtype), 1))
    assert abs(ppl_on / ppl_off - 1.0) <= 0.05, (ppl_off, ppl_on)


# ------------------------------------------------------------- composition
@pytest.mark.parametrize("qdtype", KV_DTYPES)
def test_trim_across_block_boundary_requantizes(model_and_params, qdtype):
    """Speculative rollback across a block boundary: the freed block
    returns to the pool, the partial block re-quantizes on the next
    write, and decoding continues — both representations."""
    model, params = model_and_params
    rng = np.random.default_rng(4)
    eng = make_engine(model, params, quant=True, qdtype=qdtype)
    uid = 7
    eng.put([uid], [rand_prompt(rng, 30)])       # seen=30 (2 blocks)
    eng.put([uid], [rand_prompt(rng, 5)])        # seen=35 (3 blocks)
    seq = eng.state_manager.get_sequence(uid)
    assert (seq.seen_tokens, len(seq.kv_blocks)) == (35, 3)
    free0 = eng.occupancy()["free_blocks"]
    assert eng.trim_sequence(uid, 7) == 1        # 35 -> 28: drops block 2
    assert (seq.seen_tokens, len(seq.kv_blocks)) == (28, 2)
    assert eng.occupancy()["free_blocks"] == free0 + 1
    # rewrite across the trimmed region and keep decoding
    logits = np.asarray(eng.put([uid], [rand_prompt(rng, 10)]))
    assert logits.shape == (1, VOCAB)
    assert seq.seen_tokens == 38
    eng.flush(uid)
    assert eng.occupancy()["in_use_blocks"] == 0


@pytest.mark.parametrize("qdtype", KV_DTYPES)
def test_spec_decode_composes_bounded(model_and_params, qdtype):
    """Speculation over a quantized cache: mechanically sound (propose/
    verify/trim) and bounded-divergent vs plain greedy on the SAME
    quantized engine config (byte-losslessness is a bf16-cache guarantee
    — trim cannot roll back a monotone scale, documented). The dtype
    axis exercises spec-decode trim over fp8 pools too."""
    model, params = model_and_params
    rng = np.random.default_rng(5)
    motif = rand_prompt(rng, 5)
    prompts = [motif * 5 + rand_prompt(rng, 3) for _ in range(2)]
    plain = greedy_generate(make_engine(model, params, quant=True,
                                        qdtype=qdtype),
                            prompts, uid_base=1, max_new_tokens=20)
    sched = ContinuousBatchingScheduler(
        make_engine(model, params, quant=True, qdtype=qdtype),
        proposer=NGramProposer(ngram_max=3), max_draft_tokens=4)
    spec = greedy_generate(prompts=prompts, uid_base=1, max_new_tokens=20,
                           scheduler=sched)
    stats = sched.spec_stats()
    assert stats["proposed"] > 0 and stats["accepted"] > 0
    fracs = []
    for a, b in zip(plain, spec):
        matched = next((i for i, (x, y) in enumerate(zip(a, b)) if x != y),
                       min(len(a), len(b)))
        fracs.append(matched / max(1, len(a)))
    assert np.mean(fracs) >= 0.5, f"spec divergence too large: {fracs}"


@pytest.mark.parametrize("qdtype", KV_DTYPES)
def test_prefix_shared_blocks_share_scales(model_and_params, qdtype):
    """A prefix-cache hit under kv_quant shares the quantized blocks AND
    their scale-plane entries (scales are indexed by pool block id): the
    second request re-prefills only the tail and still matches the
    uncached quantized engine's stream exactly."""
    model, params = model_and_params
    rng = np.random.default_rng(6)
    sysp = rand_prompt(rng, 40)
    tail_a, tail_b = rand_prompt(rng, 7), rand_prompt(rng, 7)
    cached = make_engine(model, params, quant=True, qdtype=qdtype,
                         enable_prefix_cache=True)
    g_warm = greedy_generate(cached, [sysp + tail_a], uid_base=100,
                             max_new_tokens=8)
    stats0 = cached.prefix_stats()
    g_hit = greedy_generate(cached, [sysp + tail_b], uid_base=200,
                            max_new_tokens=8)
    stats = cached.prefix_stats()
    assert stats["hits"] - stats0["hits"] >= 2          # blocks shared
    assert stats["tokens_saved"] - stats0["tokens_saved"] >= 2 * BS
    # same prompts through a cache-less quantized engine: identical
    # streams — dequantizing a shared block with its shared scale is
    # exactly what the writer stored
    plain = make_engine(model, params, quant=True, qdtype=qdtype)
    p_warm = greedy_generate(plain, [sysp + tail_a], uid_base=100,
                             max_new_tokens=8)
    p_hit = greedy_generate(plain, [sysp + tail_b], uid_base=200,
                            max_new_tokens=8)
    assert_greedy_parity(p_warm + p_hit, g_warm + g_hit,
                         label="prefix cache under kv_quant")


def test_cancel_frees_quantized_blocks(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(7)
    eng = make_engine(model, params, quant=True)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(1, rand_prompt(rng, 40), max_new_tokens=50)
    for _ in range(3):
        sched.step()
    assert eng.occupancy()["in_use_blocks"] > 0
    assert sched.cancel(1)
    occ = eng.occupancy()
    assert occ["in_use_blocks"] == 0
    assert occ["free_blocks"] == occ["total_blocks"]


def test_failover_resume_with_quantized_kv(model_and_params):
    """A replica crash mid-stream under kv_quant: requests fail over,
    resume from prompt + delivered tokens on the survivor's quantized
    cache, and the streams match an unfaulted quantized run."""
    from deepspeed_tpu.serving import (RequestState, ServingConfig,
                                       ServingFrontend)

    model, params = model_and_params
    rng = np.random.default_rng(8)
    prompts = [rand_prompt(rng, 20) for _ in range(4)]

    def factory(i):
        return make_engine(model, params, quant=True)

    def run(faulted):
        scfg = ServingConfig(
            max_queue_depth=64,
            kv_quant={"enabled": True},
            fault_tolerance={"enabled": True, "max_retries": 3,
                             "restart_backoff_s": 0.05,
                             "supervisor_poll_s": 0.02},
            faults=({"enabled": True, "schedule": [
                {"kind": "crash", "replica": 0, "at_step": 3}]}
                if faulted else {"enabled": False}))
        fe = ServingFrontend([factory(0), factory(1)], scfg,
                             engine_factory=factory)
        handles = [fe.submit(p, max_new_tokens=6) for p in prompts]
        assert fe.wait_all(handles, timeout=120)
        gens = [[ev.token for ev in h.drain()] for h in handles]
        states = [h.state for h in handles]
        fe.shutdown(drain=False, timeout=5)
        return gens, states

    gens_ok, _ = run(faulted=False)
    gens_chaos, states = run(faulted=True)
    assert all(s == RequestState.FINISHED for s in states)
    assert_greedy_parity(gens_ok, gens_chaos,
                         label="failover under kv_quant")


def test_configure_kv_quant_toggle_and_guard(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(9)
    eng = make_engine(model, params, quant=False)
    eng.configure_kv_quant(True)
    assert eng.state_manager.kv_quant
    assert eng.state_manager.kv_cache["k"].dtype == jnp.int8
    eng.put([1], [rand_prompt(rng, 10)])
    with pytest.raises(RuntimeError, match="tracked"):
        eng.configure_kv_quant(False)
    eng.configure_kv_quant(True)        # no-op while unchanged: fine
    eng.flush(1)
    eng.configure_kv_quant(False)
    assert set(eng.state_manager.kv_cache) == {"k", "v"}
    with pytest.raises(ValueError, match="dtype"):
        eng.configure_kv_quant(True, dtype="fp8")
    # the reserved dtype surface is now real: int8 -> fp8_e4m3 rebuilds
    # the pools at the new representation (legal while drained)
    eng.configure_kv_quant(True, dtype="fp8_e4m3")
    assert eng.state_manager.kv_cache["k"].dtype == jnp.float8_e4m3fn
    assert eng.state_manager.kv_quant_dtype == "fp8_e4m3"


# -------------------------------------------------- serving config + gauges
def test_serving_config_applies_kv_quant(model_and_params):
    from deepspeed_tpu.serving import KVQuantConfig, ServingConfig
    from deepspeed_tpu.serving import ServingFrontend

    model, params = model_and_params
    kq = KVQuantConfig(enabled=True)
    vcfg = RaggedInferenceEngineConfig()
    kq.apply(vcfg)
    assert vcfg.kv_quant_enabled and vcfg.kv_quant_dtype == "int8"
    eng = make_engine(model, params, quant=False)
    fe = ServingFrontend([eng], ServingConfig(kv_quant={"enabled": True}))
    try:
        assert eng.state_manager.kv_quant
        rng = np.random.default_rng(10)
        h = fe.submit(rand_prompt(rng, 20), max_new_tokens=4)
        assert fe.wait_all([h], timeout=60)
        snap = fe.metrics_snapshot()
        assert "kv_blocks_in_use" in snap and "kv_bytes_in_use" in snap
        # finished request freed its blocks; gauges reflect the pool
        occ = eng.occupancy()
        assert snap["kv_blocks_in_use"] == occ["in_use_blocks"]
        assert snap["kv_bytes_in_use"] == occ["bytes_in_use"]
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_ds_config_mounts_kv_quant():
    from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig

    c = DeepSpeedTpuConfig(**{"train_micro_batch_size_per_gpu": 1,
                              "kv_quant": {"enabled": True},
                              "serving": {"kv_quant": {"enabled": True}}})
    assert c.kv_quant.enabled and c.serving.kv_quant.enabled
    assert c.kv_quant.dtype == "int8"


def test_tp_sharded_quant_matches_single_device(model_and_params):
    """TP serving with quantized pools: the shard_map in/out specs carry
    the scale operands (sharded over kv-heads like the pools), so a
    TP-sharded quant engine must match the single-device quant engine
    exactly — same int8 pools, same scales, same logits."""
    from deepspeed_tpu.parallel import topology as topo

    model, params = model_and_params
    single = make_engine(model, params, quant=True)
    topo.reset_topology()
    t = topo.MeshTopology.build(data=4, tensor=2)
    sharded = InferenceEngineV2(
        model, params=params, mesh=t,
        config=RaggedInferenceEngineConfig(
            max_ragged_batch_size=256, max_ragged_sequence_count=8,
            max_chunk_tokens=32, kv_blocks=64, kv_block_size=BS,
            max_tracked_sequences=64, kv_quant_enabled=True))
    assert sharded.state_manager.kv_cache["k"].dtype == jnp.int8
    rng = np.random.default_rng(12)
    prompts = {1: rand_prompt(rng, 7), 2: rand_prompt(rng, 12)}
    for uid, p in prompts.items():
        a = np.asarray(single.put([uid], [p]))
        b = np.asarray(sharded.put([uid], [p]))
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)
    for step in range(3):
        nxt = [[int(rng.integers(0, VOCAB))] for _ in prompts]
        a = np.asarray(single.put(list(prompts), nxt))
        b = np.asarray(sharded.put(list(prompts), nxt))
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5,
                                   err_msg=f"decode step {step}")
    topo.reset_topology()


# ------------------------------------------------------- kernel-level check
def test_pallas_kernel_dequant_matches_xla(monkeypatch):
    from deepspeed_tpu.ops import paged_attention as pa

    monkeypatch.setattr(pa, "_FORCE_INTERPRET", True)
    rng = np.random.default_rng(11)
    N, C, H, KH, D, NB, bs, MB = 2, 4, 4, 2, 8, 16, 8, 4
    q = jnp.asarray(rng.normal(size=(N, C, H, D)), jnp.float32)
    kq = jnp.asarray(rng.integers(-127, 128, size=(NB, KH, bs, D)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, size=(NB, KH, bs, D)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.1, size=(NB, KH)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.1, size=(NB, KH)), jnp.float32)
    tbl = jnp.asarray(rng.permutation(NB)[:N * MB].reshape(N, MB), jnp.int32)
    sp = jnp.asarray([5, 12], jnp.int32)
    nt = jnp.asarray([4, 4], jnp.int32)
    ref = pa.paged_attention_xla(q, kq, vq, tbl, sp, nt,
                                 k_scale=ks, v_scale=vs)
    out = pa.paged_attention(q, kq, vq, tbl, sp, nt,
                             k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # the quantized XLA gather equals dense attention over the
    # dequantized pools — dequantization is exact, not approximate
    kf = kq.astype(jnp.float32) * ks[:, :, None, None]
    vf = vq.astype(jnp.float32) * vs[:, :, None, None]
    dense = pa.paged_attention_xla(q, kf, vf, tbl, sp, nt)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(dense))


# ------------------------------------------------------ bench schema check
def test_bench_schema_validator():
    import importlib
    import os
    import sys

    os.environ.setdefault("BENCH_TIMEOUT_S", "0")   # no watchdog in tests
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    bench = importlib.import_module("bench")
    occ = {k: 1 for k in bench._OCCUPANCY_KEYS}
    good = {"kv_quant": {"max_concurrent_base": 8, "max_concurrent_int8": 16,
                         "max_concurrent_fp8": 16,
                         "concurrency_ratio": 2.0, "budget_bytes": 1024,
                         "ppl_base": 1.0, "ppl_int8": 1.0, "ppl_fp8": 1.0,
                         "ppl_ratio": 1.0, "ppl_ratio_fp8": 1.0,
                         "ppl_gate_ok": True, "ppl_gate_ok_fp8": True,
                         "greedy_parity": True,
                         "mean_matched_prefix_frac": 1.0,
                         "mean_matched_prefix_frac_fp8": 1.0,
                         "disabled_parity": True, "kv_occupancy": occ}}
    good["weight_quant"] = {
        "param_bytes_fp32": 4096, "param_bytes_int8": 1024,
        "weight_compression_x": 4.0, "bytes_gate_ok": True,
        "host_byte_budget": 1 << 20,
        "replicas_at_budget_base": 2, "replicas_at_budget_int8": 8,
        "prefill_ttft_base_ms": 9.0, "prefill_ttft_int8_ms": 8.0,
        "decode_tpot_base_ms": 2.0, "decode_tpot_int8_ms": 1.8,
        "ppl_base": 1.0, "ppl_int8": 1.0, "ppl_ratio": 1.0,
        "ppl_gate_ok": True, "mean_matched_prefix_frac": 1.0,
        "greedy_parity": True, "disabled_parity": True,
        "kv_occupancy": dict(occ)}
    for name in bench._STAMPED_PHASES:
        if name in ("kv_quant", "weight_quant", "train_chaos", "disagg",
                    "slo", "kv_tier", "overload", "autoscale", "fabric"):
            continue            # typed phases built explicitly
        good[name] = {"kv_occupancy": dict(occ)}
    good["kv_tier"] = {"tier_on_p50_ttft_ms": 10.7,
                       "tier_off_p50_ttft_ms": 14.1,
                       "ttft_improved": True, "blocks_spilled": 64,
                       "blocks_restored": 64, "blocks_dropped": 0,
                       "prefix_hit_rate_on": 0.89,
                       "prefix_hit_rate_off": 0.0,
                       "greedy_parity": True, "disabled_parity": True,
                       "kv_occupancy": dict(occ)}
    good["slo"] = {"alert_fired": True, "alert_resolved": True,
                   "fire_to_resolve_s": 4.9, "alerts_firing_peak": 1,
                   "alerts_firing_final": 0, "window_p95_ttft_ms": 12.5,
                   "cum_p95_ttft_ms": 12.5, "window_agrees": True,
                   "noise_floor_pct": 1.0, "overhead_slo_pct": 0.3,
                   "overhead_ok": True, "journal_events": 2,
                   "journal_schema_ok": True, "disabled_parity": True,
                   "kv_occupancy": dict(occ)}
    good["train_chaos"] = {"recovery_time_s": 0.12, "steps_lost": 1,
                           "resume_parity": True,
                           "sigterm_resume_parity": True,
                           "injectors_off_parity": True, "restarts": 1,
                           "n_steps": 8, "crash_at_step": 5,
                           "urgent_save_s": 0.01,
                           "kv_occupancy": dict(occ)}
    good["disagg"] = {"handoffs_completed": 13, "handoff_fallbacks": 0,
                      "tpot_improved": True, "handoff_parity": True,
                      "disabled_parity": True, "replicas": 4,
                      "decode_reserve_tokens": 8,
                      "kv_occupancy": dict(occ)}
    good["overload"] = {"n_requests": 24, "kv_blocks": 8,
                        "overload_ratio": 10.25,
                        "oversubscription_factor": 2.5,
                        "zero_wedges": True, "completed_on": 24,
                        "completed_off": 0,
                        "completed_per_sec_on": 9.6,
                        "completed_per_sec_off": 0.0,
                        "sequences_preempted": 12,
                        "sequences_resumed": 12,
                        "p95_interactive_ttft_ms": 2500.0,
                        "p99_interactive_ttft_ms": 2500.0,
                        "p95_interactive_tpot_ms": 2.4,
                        "p99_interactive_tpot_ms": 2.5,
                        "preempt_parity": True, "disabled_parity": True,
                        "kv_occupancy": dict(occ)}
    good["autoscale"] = {"n_requests": 30, "min_replicas": 1,
                         "max_replicas": 3, "static_replicas": 3,
                         "slo_attainment_elastic": 1.0,
                         "slo_attainment_static": 1.0,
                         "attainment_ok": True,
                         "replica_seconds_elastic": 16.2,
                         "replica_seconds_static": 21.9,
                         "elastic_beats_static_cost": True,
                         "scale_ups": 2, "scale_downs": 2, "reroles": 0,
                         "peak_replicas": 3, "final_replicas": 1,
                         "requests_evacuated": 0,
                         "greedy_parity": True, "disabled_parity": True,
                         "kv_occupancy": dict(occ)}
    good["fabric"] = {"replicas": 2, "n_requests": 8, "prompt_len": 24,
                      "max_new": 8, "chunk_blocks": 1,
                      "local_p50_ttft_ms": 1287.3,
                      "local_p95_ttft_ms": 1287.4,
                      "local_p50_tpot_ms": 2.3, "local_p95_tpot_ms": 3.5,
                      "fabric_p50_ttft_ms": 1967.6,
                      "fabric_p95_ttft_ms": 1989.7,
                      "fabric_p50_tpot_ms": 3.4,
                      "fabric_p95_tpot_ms": 169.7,
                      "rpc_calls": 22, "rpc_p50_ms": 0.8,
                      "rpc_p95_ms": 175.0,
                      "rpc_overhead_p50_ttft_ms": 680.3,
                      "handoffs_completed_local": 10,
                      "handoffs_completed_fabric": 10,
                      "handoff_fallbacks_fabric": 0,
                      "handle_disconnects": 0,
                      "parity": True, "disabled_parity": True,
                      "zero_wedges": True, "kv_occupancy": dict(occ)}
    good["multitenant"] = {"n_flood": 12, "n_interactive": 5,
                           "flood_max_new": 10, "interactive_max_new": 6,
                           "solo_p95_ttft_ms": 1635.7,
                           "fair_on_p95_ttft_ms": 1921.0,
                           "fair_off_p95_ttft_ms": 2158.6,
                           "isolation_ratio_on": 1.174,
                           "starvation_ratio_off": 1.32,
                           "isolation_ok": True,
                           "flood_tokens_on": 120,
                           "flood_progress_ok": True,
                           "fair_beats_off": True,
                           "tenant_b_submitted": 5, "tenant_b_shed": 0,
                           "zero_wedges": True,
                           "greedy_parity": True, "disabled_parity": True,
                           "kv_occupancy": dict(occ)}
    good["affinity"] = {"n_requests": 72, "n_replicas": 3,
                        "n_families": 9, "shared_prefix_tokens": 112,
                        "max_new": 3,
                        "affinity_on_p50_ttft_ms": 44.3,
                        "affinity_on_p95_ttft_ms": 1591.1,
                        "affinity_off_p50_ttft_ms": 91.5,
                        "affinity_off_p95_ttft_ms": 1869.8,
                        "ttft_improved": True,
                        "prefix_tokens_saved_on": 5600,
                        "prefix_tokens_saved_off": 2352,
                        "tokens_saved_improved": True,
                        "affinity_hits": 50, "affinity_misses": 22,
                        "share_cap_ok": True,
                        "warmup_blocks": 32, "warmup_s": 0.49,
                        "warmup_first_hit_ok": True,
                        "predictive_first_grow_tick": 5,
                        "watermark_first_grow_tick": 8,
                        "predictive_earlier": True,
                        "predictive_peak_queue": 28.0,
                        "watermark_peak_queue": 35.5,
                        "predictive_no_flap": True,
                        "greedy_parity": True, "disabled_parity": True,
                        "kv_occupancy": dict(occ)}
    good["federation"] = {"frontends": 2, "n_requests": 8,
                          "prompt_len": 24, "max_new": 8,
                          "exported_replicas": 1,
                          "requests_federated": 4,
                          "standalone_p50_ttft_ms": 3379.3,
                          "standalone_p95_ttft_ms": 3647.8,
                          "federated_p50_ttft_ms": 3271.0,
                          "federated_p95_ttft_ms": 3568.3,
                          "peer_rpc_calls": 5, "peer_rpc_p50_ms": 0.6,
                          "peer_rpc_p95_ms": 1.0,
                          "kill_n_requests": 4, "kill_max_new": 96,
                          "requests_failed_over": 2,
                          "failover_recovery_s": 0.268,
                          "parity": True, "kill_parity": True,
                          "disabled_parity": True, "zero_wedges": True,
                          "kv_occupancy": dict(occ)}
    good["fleet_obs"] = {"replicas": 2, "n_requests": 8,
                         "prompt_len": 24, "max_new": 6,
                         "wall_off_s": 0.272, "wall_off_rerun_s": 0.302,
                         "wall_on_s": 0.282, "noise_floor_pct": 11.4,
                         "overhead_enabled_pct": 3.9,
                         "spans_total": 192, "server_spans": 16,
                         "spans_forwarded": 68,
                         "min_ttft_coverage": 0.999,
                         "ttft_coverage_ok": True,
                         "chains_complete": True,
                         "trace_path": "/tmp/trace_fleet_1.json",
                         "trace_valid": True, "journal_sources": 2,
                         "journal_events_forwarded": 6,
                         "journal_events_dropped": 0,
                         "journal_exactly_once": True,
                         "clock_offset_ms": 0.08,
                         "http_metrics_ok": True, "http_health_ok": True,
                         "fleetctl_ok": True, "parity": True,
                         "disabled_parity": True, "zero_wedges": True,
                         "kv_occupancy": dict(occ)}
    good["net_chaos"] = {"replicas": 3, "n_requests": 9,
                         "prompt_len": 24, "max_new": 6,
                         "completed_under_chaos": 1.0,
                         "recovery_time_s": 1.666,
                         "quarantines_journaled": 1,
                         "readmits_journaled": 1,
                         "frames_corrupt": 3,
                         "frames_corrupt_fatal": 0,
                         "faults_injected": 40,
                         "parity": True, "disabled_parity": True,
                         "kv_occupancy": dict(occ)}
    assert bench.validate_serving_schema(good) == []
    # multitenant typed checks: bool-for-int rejected, missing named
    bad_mt = dict(good)
    bad_mt["multitenant"] = {"n_flood": True, "isolation_ok": 1}
    problems_mt = bench.validate_serving_schema(bad_mt)
    assert any("multitenant.n_flood" in p for p in problems_mt)
    assert any("multitenant.isolation_ok" in p for p in problems_mt)
    assert any("multitenant.fair_beats_off: missing" in p
               for p in problems_mt)
    # affinity typed checks: bool-for-int rejected, missing named
    bad_af = dict(good)
    bad_af["affinity"] = {"affinity_hits": True, "share_cap_ok": 1}
    problems_af = bench.validate_serving_schema(bad_af)
    assert any("affinity.affinity_hits" in p for p in problems_af)
    assert any("affinity.share_cap_ok" in p for p in problems_af)
    assert any("affinity.warmup_first_hit_ok: missing" in p
               for p in problems_af)
    # federation typed checks: bool-for-int rejected, missing named
    bad_fd = dict(good)
    bad_fd["federation"] = {"requests_federated": True, "kill_parity": 1}
    problems_fd = bench.validate_serving_schema(bad_fd)
    assert any("federation.requests_federated" in p for p in problems_fd)
    assert any("federation.kill_parity" in p for p in problems_fd)
    assert any("federation.failover_recovery_s: missing" in p
               for p in problems_fd)
    # fleet_obs typed checks: bool-for-int rejected, missing named
    bad_fo = dict(good)
    bad_fo["fleet_obs"] = {"journal_sources": True, "fleetctl_ok": 1}
    problems_fo = bench.validate_serving_schema(bad_fo)
    assert any("fleet_obs.journal_sources" in p for p in problems_fo)
    assert any("fleet_obs.fleetctl_ok" in p for p in problems_fo)
    assert any("fleet_obs.min_ttft_coverage: missing" in p
               for p in problems_fo)
    # fabric typed checks: bool-for-int rejected, missing fields named
    bad_fb = dict(good)
    bad_fb["fabric"] = {"rpc_calls": True, "parity": 1}
    problems_fb = bench.validate_serving_schema(bad_fb)
    assert any("fabric.rpc_calls" in p for p in problems_fb)
    assert any("fabric.parity" in p for p in problems_fb)
    assert any("fabric.zero_wedges: missing" in p for p in problems_fb)
    # autoscale typed checks: bool-for-int rejected, missing named
    bad_as = dict(good)
    bad_as["autoscale"] = {"scale_ups": True, "attainment_ok": 1}
    problems_as = bench.validate_serving_schema(bad_as)
    assert any("autoscale.scale_ups" in p for p in problems_as)
    assert any("autoscale.attainment_ok" in p for p in problems_as)
    assert any("autoscale.greedy_parity: missing" in p
               for p in problems_as)
    # overload typed checks: bool-for-int rejected, missing fields named
    bad_ov = dict(good)
    bad_ov["overload"] = {"completed_on": True, "zero_wedges": 1}
    problems_ov = bench.validate_serving_schema(bad_ov)
    assert any("overload.completed_on" in p for p in problems_ov)
    assert any("overload.zero_wedges" in p for p in problems_ov)
    assert any("overload.preempt_parity: missing" in p
               for p in problems_ov)
    # disagg typed checks: missing and mistyped fields are named
    bad_dg = dict(good)
    bad_dg["disagg"] = {"handoffs_completed": True, "handoff_parity": 1}
    problems_dg = bench.validate_serving_schema(bad_dg)
    assert any("disagg.handoffs_completed" in p for p in problems_dg)
    assert any("disagg.handoff_parity" in p for p in problems_dg)
    assert any("disagg.disabled_parity: missing" in p for p in problems_dg)
    # kv_tier typed checks: missing and mistyped (bool-for-int) named
    bad_kt = dict(good)
    bad_kt["kv_tier"] = {"blocks_restored": True, "greedy_parity": 1}
    problems_kt = bench.validate_serving_schema(bad_kt)
    assert any("kv_tier.blocks_restored" in p for p in problems_kt)
    assert any("kv_tier.greedy_parity" in p for p in problems_kt)
    assert any("kv_tier.disabled_parity: missing" in p
               for p in problems_kt)
    # skipped phases are exempt from field checks
    skipped = dict(good)
    skipped["chaos"] = {"phase_skipped": "phase budget 240s exceeded"}
    assert bench.validate_serving_schema(skipped) == []
    # missing/garbled fields are named
    bad = dict(good)
    bad["kv_quant"] = {"max_concurrent_base": "eight"}
    problems = bench.validate_serving_schema(bad)
    assert any("max_concurrent_base" in p for p in problems)
    assert any("concurrency_ratio: missing" in p for p in problems)
    bad2 = dict(good)
    bad2["prefix"] = {"n_requests": 1}
    assert any("prefix.kv_occupancy" in p
               for p in bench.validate_serving_schema(bad2))
    # train_chaos typed checks: wrong types and missing fields are named,
    # a bool where an int is expected is rejected, a skip stamp is exempt
    bad3 = dict(good)
    bad3["train_chaos"] = {"recovery_time_s": "fast", "steps_lost": True,
                           "kv_occupancy": dict(occ)}
    problems3 = bench.validate_serving_schema(bad3)
    assert any("train_chaos.recovery_time_s" in p for p in problems3)
    assert any("train_chaos.steps_lost" in p for p in problems3)
    assert any("train_chaos.resume_parity: missing" in p for p in problems3)
    skipped2 = dict(good)
    skipped2["train_chaos"] = {"phase_skipped": "not selected"}
    assert bench.validate_serving_schema(skipped2) == []
    # the shared typed-phase checker applies the bool guard to kv_quant
    # too: a bool where an int is expected is named, not silently passed
    bad4 = dict(good)
    bad4["kv_quant"] = dict(good["kv_quant"], max_concurrent_base=True)
    assert any("kv_quant.max_concurrent_base" in p
               for p in bench.validate_serving_schema(bad4))
    # weight_quant typed checks: bool-for-int rejected, missing named
    bad_wq = dict(good)
    bad_wq["weight_quant"] = {"param_bytes_fp32": True, "bytes_gate_ok": 1}
    problems_wq = bench.validate_serving_schema(bad_wq)
    assert any("weight_quant.param_bytes_fp32" in p for p in problems_wq)
    assert any("weight_quant.bytes_gate_ok" in p for p in problems_wq)
    assert any("weight_quant.disabled_parity: missing" in p
               for p in problems_wq)
    # slo typed checks: missing/mistyped fields named; a journal that
    # failed validate_events is a schema problem in its own right
    bad5 = dict(good)
    bad5["slo"] = {"alert_fired": 1, "kv_occupancy": dict(occ)}
    problems5 = bench.validate_serving_schema(bad5)
    assert any("slo.alert_fired" in p for p in problems5)
    assert any("slo.journal_schema_ok: missing" in p for p in problems5)
    bad6 = dict(good)
    bad6["slo"] = dict(good["slo"], journal_schema_ok=False)
    assert any("journal events failed schema" in p
               for p in bench.validate_serving_schema(bad6))
    skipped3 = dict(good)
    skipped3["slo"] = {"phase_skipped": "not selected"}
    assert bench.validate_serving_schema(skipped3) == []


def test_phase_runner_skip_and_budget(tmp_path, monkeypatch):
    import importlib
    import sys

    monkeypatch.setenv("BENCH_TIMEOUT_S", "0")
    sys.path.insert(0, str(tmp_path.parent))  # no-op, keeps sys.path sane
    bench = importlib.import_module("bench")
    monkeypatch.setenv("BENCH_PHASE_DIR", str(tmp_path))
    monkeypatch.setenv("BENCH_PHASE_TIMEOUT_S", "1")
    monkeypatch.delenv("BENCH_PHASES", raising=False)
    monkeypatch.delenv("BENCH_RESUME", raising=False)
    runner = bench.PhaseRunner(stamp=lambda: {"total_blocks": 1})
    # a phase that exceeds its budget degrades to a stamp, and later
    # phases in the SAME process skip too (the abandoned worker may
    # still be mutating shared engine state — racing it would corrupt
    # their numbers); skip stamps are never cached as artifacts
    import time as _t
    out = runner.run("wedge", lambda: _t.sleep(10))
    assert "budget" in out["phase_skipped"]
    assert out["kv_occupancy"] == {"total_blocks": 1}
    after_wedge = runner.run("after-wedge", lambda: {"x": 9})
    assert "prior phase wedged" in after_wedge["phase_skipped"]
    assert not (tmp_path / "phase_wedge.json").exists()
    # a completing phase writes its artifact; resume loads it
    out = bench.PhaseRunner().run("quick", lambda: {"x": 1})
    assert out["x"] == 1 and (tmp_path / "phase_quick.json").exists()
    monkeypatch.setenv("BENCH_RESUME", "1")
    runner2 = bench.PhaseRunner()
    cached = runner2.run("quick", lambda: {"x": 2})
    assert cached["x"] == 1 and cached["phase_cached"]
    # backend loss short-circuits later phases with an explicit stamp
    monkeypatch.delenv("BENCH_RESUME", raising=False)
    runner3 = bench.PhaseRunner()

    def die():
        raise RuntimeError("UNAVAILABLE: tunnel gone")

    out = runner3.run("dead", die)
    assert out["phase_skipped"].startswith("tpu_backend_lost")
    out2 = runner3.run("after", lambda: {"x": 3})
    assert out2["phase_skipped"].startswith("tpu_backend_lost")
