"""Evoformer attention tests (reference tests/unit/ops/deepspeed4science/
test_DS4Sci_EvoformerAttention.py): parity against a naive per-head
reference with both bias kinds, gradient flow into Q/K/V and the biases."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.evoformer_attn import (DS4Sci_EvoformerAttention,
                                              evoformer_attention)
from deepspeed_tpu.ops.spatial import (nhwc_bias_add, nhwc_bias_add_add,
                                       nhwc_bias_add_bias_add)


def naive_evoformer(q, k, v, biases):
    """Independent loop formulation of the AlphaFold attention."""
    q, k, v = (np.asarray(x, np.float64) for x in (q, k, v))
    B, N, L, H, D = q.shape
    out = np.zeros_like(q)
    for b in range(B):
        for n in range(N):
            for h in range(H):
                logits = q[b, n, :, h] @ k[b, n, :, h].T / np.sqrt(D)
                for bias in biases:
                    bb = np.asarray(bias, np.float64)
                    bb = np.broadcast_to(bb, (B, N, H, L, L))
                    logits = logits + bb[b, n, h]
                e = np.exp(logits - logits.max(-1, keepdims=True))
                p = e / e.sum(-1, keepdims=True)
                out[b, n, :, h] = p @ v[b, n, :, h]
    return out


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    B, N, L, H, D = 2, 3, 20, 4, 8
    mk = lambda: jnp.asarray(rng.normal(size=(B, N, L, H, D))
                             .astype(np.float32))
    return mk(), mk(), mk()


def test_no_bias_parity(qkv):
    q, k, v = qkv
    out = evoformer_attention(q, k, v)
    ref = naive_evoformer(q, k, v, [])
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_mask_and_pair_bias_parity(qkv):
    q, k, v = qkv
    B, N, L, H, _ = q.shape
    rng = np.random.default_rng(1)
    bias1 = jnp.asarray(rng.normal(size=(B, N, 1, 1, L))
                        .astype(np.float32))        # MSA mask bias
    bias2 = jnp.asarray(rng.normal(size=(B, 1, H, L, L))
                        .astype(np.float32))        # triangle pair bias
    out = DS4Sci_EvoformerAttention(q, k, v, [bias1, bias2])
    ref = naive_evoformer(q, k, v, [bias1, bias2])
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_bias_gradients_flow(qkv):
    q, k, v = qkv
    B, N, L, H, _ = q.shape
    bias2 = jnp.zeros((B, 1, H, L, L), jnp.float32)

    def loss(q, k, v, b2):
        return evoformer_attention(q, k, v, [None, b2]).sum()

    grads = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, bias2)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


def test_rejects_three_biases(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="at most two"):
        evoformer_attention(q, k, v, [None, None, None])


def test_spatial_bias_adds():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 8)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))
    other = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))
    ob = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(nhwc_bias_add(x, b)),
                               np.asarray(x) + np.asarray(b))
    np.testing.assert_allclose(np.asarray(nhwc_bias_add_add(x, b, other)),
                               np.asarray(x) + np.asarray(b)
                               + np.asarray(other))
    np.testing.assert_allclose(
        np.asarray(nhwc_bias_add_bias_add(x, b, other, ob)),
        np.asarray(x) + np.asarray(b) + np.asarray(other) + np.asarray(ob))
