"""Config-system tests (reference analogues: tests/unit/runtime/test_ds_config_dict.py)."""

import json

import pytest

from deepspeed_tpu.runtime.config import (
    DeepSpeedConfigError,
    DeepSpeedTpuConfig,
    load_config,
)


def test_defaults():
    cfg = load_config(None)
    assert cfg.zero_optimization.stage == 0
    assert not cfg.fp16.enabled
    assert not cfg.bf16.enabled
    assert cfg.precision.value == "fp32"


def test_dict_config():
    cfg = load_config({
        "train_batch_size": 32,
        "gradient_accumulation_steps": 2,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "offload_optimizer": {"device": "cpu"}},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
        "gradient_clipping": 1.0,
    })
    assert cfg.train_batch_size == 32
    assert cfg.zero_optimization.stage == 2
    assert cfg.zero_optimization.offload_optimizer.device.value == "cpu"
    assert cfg.optimizer.type == "AdamW"
    assert cfg.precision.value == "bf16"
    assert cfg.gradient_clipping == 1.0


def test_json_file_config(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps({"train_micro_batch_size_per_gpu": 4, "fp16": {"enabled": True}}))
    cfg = load_config(str(p))
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.precision.value == "fp16"


def test_batch_resolution():
    cfg = load_config({"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4})
    cfg.resolve_batch_sizes(dp_world_size=8)
    assert cfg.gradient_accumulation_steps == 2

    cfg = load_config({"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 3})
    cfg.resolve_batch_sizes(dp_world_size=8)
    assert cfg.train_batch_size == 96

    cfg = load_config({"train_batch_size": 64})
    cfg.resolve_batch_sizes(dp_world_size=8)
    assert cfg.train_micro_batch_size_per_gpu == 8
    assert cfg.gradient_accumulation_steps == 1


def test_batch_resolution_inconsistent():
    cfg = load_config({
        "train_batch_size": 65, "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
    })
    with pytest.raises(DeepSpeedConfigError):
        cfg.resolve_batch_sizes(dp_world_size=8)


def test_no_batch_size():
    cfg = load_config({})
    with pytest.raises(DeepSpeedConfigError):
        cfg.resolve_batch_sizes(dp_world_size=8)


def test_legacy_cpu_offload_flag():
    cfg = load_config({"zero_optimization": {"stage": 2, "cpu_offload": True}})
    assert cfg.zero_optimization.offload_optimizer is not None
    assert cfg.zero_optimization.offload_optimizer.device.value == "cpu"


def test_duplicate_keys_rejected(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"train_batch_size": 1, "train_batch_size": 2}')
    with pytest.raises(ValueError):
        load_config(str(p))


def test_mesh_config():
    cfg = load_config({"mesh": {"fsdp": 4, "tensor": 2, "data": 1}})
    assert cfg.mesh.fsdp == 4
    assert cfg.mesh.tensor == 2
