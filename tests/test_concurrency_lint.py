"""Concurrency lint + RankedLock runtime tests (docs/CONCURRENCY.md).

Four layers:

- **Fixture snippets** (seeded mutations): each static check class —
  guarded miss, helper indirection (one level AND chained), writes-only
  mode, rank inversion, lock cycle, blocking-while-locked (direct and
  one call level deep), declared-name audits, stale/unjustified
  baseline — demonstrated on minimal sources the analyzer must flag (or
  must NOT flag, for the legal patterns).
- **Whole-repo gate**: ``run_repo(REPO)`` returns zero non-baselined
  findings — the same invariant ``scripts/lint_concurrency.py`` gates
  tier-1 on.
- **Regression tests** for the real findings this lint surfaced and
  fixed (queue brownout/preempt-pressure fields, flight-recorder
  cadence watermark), pinned by baseline id so the fix can't silently
  regress, plus racing-thread behavioral checks.
- **RankedLock runtime**: order enforcement, reentrancy, condition
  wait, hold-time histogram, debug-off allocation-freedom (tracemalloc)
  and the declaration audits (LOCK_RANKS vs constructions vs the
  docs/CONCURRENCY.md rank table, all both ways) — ending in a chaos
  run (supervisor kill + autoscaler churn) under debug mode asserting
  no ordering violations and no over-threshold holds.
"""

import os
import re
import threading
import time
import tracemalloc

import numpy as np
import pytest

from deepspeed_tpu.analysis import (analyze_source, apply_baseline,
                                    parse_baseline, render_baseline,
                                    run_repo)
from deepspeed_tpu.analysis.concurrency import analyze as analyze_repo
from deepspeed_tpu.analysis.declared import (_template_matches_const,
                                             _template_of,
                                             check_declared_names)
from deepspeed_tpu.utils import locks as locks_mod
from deepspeed_tpu.utils.locks import (LOCK_RANKS, LockOrderError,
                                       RankedCondition, RankedLock,
                                       disable_lock_debug,
                                       enable_lock_debug, lock_debug)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ids(findings, check=None):
    return sorted(f.baseline_id for f in findings
                  if check is None or f.check == check)


# ------------------------------------------------------ guarded fields
class TestGuardedFields:
    def test_unguarded_read_and_write_flagged(self):
        src = """
import threading

class C:
    _GUARDED_BY = {"_inflight": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = 0          # init is exempt

    def bad_read(self):
        return self._inflight

    def bad_write(self):
        self._inflight += 1

    def good(self):
        with self._lock:
            self._inflight += 1
"""
        found = analyze_source(src)
        ids = _ids(found, "guarded-field")
        assert any("C.bad_read:_inflight" in i for i in ids)
        assert any("C.bad_write:_inflight" in i for i in ids)
        assert not any("C.good" in i for i in ids)
        assert not any("C.__init__" in i for i in ids)

    def test_helper_indirection_one_level_and_chained(self):
        src = """
import threading

class C:
    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def _bump_locked(self):
        self._n += 1                 # every call site holds the lock

    def _note_locked(self):
        self._n += 1                 # called only via _bump2_locked

    def _bump2_locked(self):
        self._note_locked()          # chained helper, still guarded

    def public(self):
        with self._lock:
            self._bump_locked()
            self._bump2_locked()
"""
        assert _ids(analyze_source(src), "guarded-field") == []

    def test_helper_with_one_unlocked_call_site_flagged(self):
        src = """
import threading

class C:
    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def _bump(self):
        self._n += 1

    def locked_path(self):
        with self._lock:
            self._bump()

    def unlocked_path(self):
        self._bump()                 # poisons the caller-holds claim
"""
        ids = _ids(analyze_source(src), "guarded-field")
        assert any("C._bump:_n" in i for i in ids)

    def test_public_helper_is_an_entry_point(self):
        src = """
import threading

class C:
    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        self._n += 1                 # public: must guard internally

    def caller(self):
        with self._lock:
            self.bump()
"""
        ids = _ids(analyze_source(src), "guarded-field")
        assert any("C.bump:_n" in i for i in ids)

    def test_writes_only_mode(self):
        src = """
import threading

class C:
    _GUARDED_BY = {"flag": "_lock:writes"}

    def __init__(self):
        self._lock = threading.Lock()
        self.flag = False

    def read_free(self):
        return self.flag             # reads are lock-free by contract

    def bad_write(self):
        self.flag = True

    def good_write(self):
        with self._lock:
            self.flag = True
"""
        ids = _ids(analyze_source(src), "guarded-field")
        assert any("C.bad_write:flag" in i for i in ids)
        assert not any("C.read_free" in i for i in ids)
        assert not any("C.good_write" in i for i in ids)

    def test_guarded_by_trailing_comment(self):
        src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock

    def bad(self):
        return len(self._items)
"""
        ids = _ids(analyze_source(src), "guarded-field")
        assert any("C.bad:_items" in i for i in ids)


# ----------------------------------------------------------- lock order
class TestLockOrder:
    def test_rank_inversion_flagged(self):
        src = """
from deepspeed_tpu.utils.locks import RankedLock

class C:
    def __init__(self):
        self._outer = RankedLock("telemetry.tracer")
        self._inner = RankedLock("serving.queue")

    def bad(self):
        with self._outer:
            with self._inner:
                pass
"""
        found = analyze_source(src)
        ids = _ids(found, "lock-order")
        assert any("telemetry.tracer->serving.queue" in i for i in ids)

    def test_correct_order_clean(self):
        src = """
from deepspeed_tpu.utils.locks import RankedLock

class C:
    def __init__(self):
        self._outer = RankedLock("serving.queue")
        self._inner = RankedLock("telemetry.tracer")

    def good(self):
        with self._outer:
            with self._inner:
                pass
"""
        found = analyze_source(src)
        assert _ids(found, "lock-order") == []
        assert _ids(found, "lock-cycle") == []

    def test_cross_object_cycle_detected(self):
        src = """
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def helper_a(self):
        with self._lock:
            pass

    def step(self):
        with self._lock:
            self.b.helper_b()

class B:
    def __init__(self, a: A):
        self._lock = threading.Lock()
        self.a = a

    def helper_b(self):
        with self._lock:
            pass

    def step_back(self):
        with self._lock:
            self.a.helper_a()
"""
        found = analyze_source(src)
        cyc = _ids(found, "lock-cycle")
        assert len(cyc) == 1
        assert "A._lock" in cyc[0] and "B._lock" in cyc[0]

    def test_peer_instance_same_lock_nesting_flagged(self):
        """Two instances of one class taking each other's equally-named
        lock is the classic unordered AB-BA deadlock — it must surface
        as a self-loop cycle (unranked) instead of being skipped as
        'same lock id' (post-review fix)."""
        src = """
import threading

class R:
    def __init__(self, peer: "R"):
        self._lock = threading.Lock()
        self.peer = peer

    def grab_peer_side(self):
        with self._lock:
            pass

    def merge(self):
        with self._lock:
            self.peer.grab_peer_side()
"""
        cyc = _ids(analyze_source(src), "lock-cycle")
        assert cyc and "R._lock" in cyc[0]

    def test_equal_rank_peer_edge_fails_rank_check(self):
        src = """
from deepspeed_tpu.utils.locks import RankedLock

class Rep:
    def __init__(self, peer: "Rep"):
        self._lock = RankedLock("serving.replica")
        self.peer = peer

    def grab_peer_side(self):
        with self._lock:
            pass

    def merge(self):
        with self._lock:
            self.peer.grab_peer_side()
"""
        ids = _ids(analyze_source(src), "lock-order")
        assert any("serving.replica->serving.replica" in i for i in ids)

    def test_reentrant_same_attr_nesting_allowed(self):
        src = """
from deepspeed_tpu.utils.locks import RankedLock

class M:
    def __init__(self):
        self._lock = RankedLock("serving.router.membership",
                                reentrant=True)

    def _inner(self):
        with self._lock:
            pass

    def outer(self):
        with self._lock:
            with self._lock:      # same-object RLock re-entry: legal
                pass
            self._inner()         # self-call re-entry: legal too
"""
        found = analyze_source(src)
        assert _ids(found, "lock-order") == []
        assert _ids(found, "lock-cycle") == []

    def test_rank_check_via_call_resolution(self):
        src = """
from deepspeed_tpu.utils.locks import RankedLock

class Inner:
    def __init__(self):
        self._lock = RankedLock("serving.queue")

    def grab_inner_lock(self):
        with self._lock:
            pass

class Outer:
    def __init__(self):
        self._lock = RankedLock("serving.replica")
        self.inner = Inner()

    def bad(self):
        with self._lock:                 # rank 70
            self.inner.grab_inner_lock()   # rank 60: inversion
"""
        ids = _ids(analyze_source(src), "lock-order")
        assert any("serving.replica->serving.queue" in i for i in ids)

    def test_cross_object_lexical_nesting_flagged(self):
        """Post-review fix: `with self._lock: with replica._lock:` —
        lexically nested acquisition of ANOTHER object's lock, typed by
        a parameter annotation or a constructor-typed attribute — joins
        the order graph instead of being invisible."""
        src = """
from deepspeed_tpu.utils.locks import RankedLock

class Rep:
    def __init__(self):
        self._lock = RankedLock("serving.queue")

class Router:
    def __init__(self):
        self._lock = RankedLock("serving.replica")
        self.rep = Rep()

    def via_attr(self):
        with self._lock:                  # rank 70
            with self.rep._lock:          # rank 60: inversion
                pass

    def via_param(self, r: Rep):
        with self._lock:
            with r._lock:
                pass
"""
        ids = _ids(analyze_source(src), "lock-order")
        assert sum("serving.replica->serving.queue" in i
                   for i in ids) == 2

    def test_escaped_method_reference_grounds_helper_cycle(self):
        """Post-review fix: a helper whose reference escapes (callback
        wiring) is an entry point — a closed helper-call cycle must not
        keep the optimistic caller-holds seed."""
        src = """
import threading

class C:
    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self.cb = self._a          # escapes: may run lock-free

    def _a(self):
        self._n += 1
        self._b()

    def _b(self):
        self._a()
"""
        ids = _ids(analyze_source(src), "guarded-field")
        assert any("C._a:_n" in i for i in ids)


# ------------------------------------------------- blocking while locked
class TestBlockingWhileLocked:
    def test_direct_blocking_ops_flagged(self):
        src = """
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.thread = threading.Thread(target=lambda: None)

    def sleepy(self):
        with self._lock:
            time.sleep(1)

    def joiny(self):
        with self._lock:
            self.thread.join(1)

    def waity(self):
        with self._lock:
            self._stop.wait(1)

    def io(self):
        with self._lock:
            open("/tmp/x")
"""
        ids = _ids(analyze_source(src), "blocking-while-locked")
        assert any("C.sleepy:time.sleep" in i for i in ids)
        assert any("C.joiny:join" in i for i in ids)
        assert any("C.waity:wait" in i for i in ids)
        assert any("C.io:open" in i for i in ids)

    def test_condition_wait_on_held_lock_allowed(self):
        src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Condition()

    def pop(self):
        with self._lock:
            self._lock.wait(0.1)     # releases while waiting: legal
"""
        assert _ids(analyze_source(src), "blocking-while-locked") == []

    def test_one_level_call_indirection(self):
        src = """
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def _drain(self):
        time.sleep(0.1)              # no lock held HERE

    def admin(self):
        with self._lock:
            self._drain()            # ...but held at the call site
"""
        found = analyze_source(src)
        ids = _ids(found, "blocking-while-locked")
        # the stable token is the CALLEE name alone (the op list depends
        # on which unique-name candidates exist elsewhere; the baseline
        # id must survive unrelated file additions) — the op still
        # appears in the human-facing detail
        assert any(i.endswith("C.admin:_drain") for i in ids)
        detail = next(f.detail for f in found
                      if f.baseline_id.endswith("C.admin:_drain"))
        assert "time.sleep" in detail


# ------------------------------------------------------------- baseline
class TestBaseline:
    GOOD = (
        "[[finding]]\n"
        'id = "guarded-field:a.py:C.m:_x"\n'
        'justification = "audited: single-writer by construction"\n'
    )

    def _finding(self):
        from deepspeed_tpu.analysis import Finding

        return Finding("guarded-field", "a.py", 3, "C.m", "_x", "read")

    def test_suppression_and_stale_detection(self):
        entries, problems = parse_baseline(self.GOOD)
        assert problems == []
        active, suppressed = apply_baseline([self._finding()], entries)
        assert active == [] and len(suppressed) == 1
        # same baseline, no findings -> the entry is stale = an error
        active, suppressed = apply_baseline([], entries)
        assert [f.check for f in active] == ["stale-baseline"]
        assert suppressed == []

    def test_missing_justification_is_an_error(self):
        text = ('[[finding]]\n'
                'id = "guarded-field:a.py:C.m:_x"\n'
                'justification = ""\n')
        _, problems = parse_baseline(text)
        assert [p.check for p in problems] == ["baseline-unjustified"]

    def test_scoped_run_reports_no_stale_entries(self):
        """A path-scoped run cannot tell 'healed' from 'out of scope':
        baseline entries for files outside the analyzed paths must NOT
        be reported as stale (post-review fix — following the stale
        advice would delete audited justifications)."""
        active, _ = run_repo(REPO, paths=["deepspeed_tpu/telemetry"])
        assert [f for f in active if f.check == "stale-baseline"] == []

    def test_update_baseline_refuses_scoped_paths(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "lint_cli", os.path.join(REPO, "scripts",
                                     "lint_concurrency.py"))
        cli = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cli)
        rc = cli.main(["--update-baseline", "deepspeed_tpu/telemetry"])
        assert rc == 2
        # the audited baseline was not touched
        entries, problems = parse_baseline(
            open(os.path.join(REPO,
                              "deepspeed_tpu/analysis/baseline.toml")).read())
        assert problems == [] and len(entries) == 7
        assert not any("UNAUDITED" in e.justification for e in entries)

    def test_render_preserves_justifications(self):
        entries, _ = parse_baseline(self.GOOD)
        text = render_baseline([self._finding()], entries)
        assert "audited: single-writer by construction" in text
        # a new finding gets a visible UNAUDITED placeholder
        from deepspeed_tpu.analysis import Finding

        new = Finding("lock-order", "b.py", 1, "D.n", "x->y", "inversion")
        text = render_baseline([self._finding(), new], entries)
        assert "UNAUDITED" in text
        reparsed, problems = parse_baseline(text)
        assert problems == [] and len(reparsed) == 2


# ------------------------------------------------------- declared names
class TestDeclaredNames:
    def test_template_matching(self):
        import ast

        tpl = _template_of(ast.parse('f"ttft_s_class_{c}"',
                                     mode="eval").body)
        assert _template_matches_const(tpl, "ttft_s_class_interactive")
        assert not _template_matches_const(tpl, "tpot_s_class_interactive")
        assert not _template_matches_const(tpl, "ttft_s_class_")

    def _mini_repo(self, tmp_path, app_src):
        pkg = tmp_path / "deepspeed_tpu"
        for sub in ("utils", "serving", "telemetry", "analysis"):
            (pkg / sub).mkdir(parents=True)
            (pkg / sub / "__init__.py").write_text("")
        (pkg / "__init__.py").write_text("")
        (pkg / "utils" / "locks.py").write_text(
            'LOCK_RANKS = {"a.outer": 10, "a.inner": 20}\n')
        (pkg / "serving" / "metrics.py").write_text(
            "def serving_metrics(classes=(\"interactive\",)):\n"
            "    reg = object()\n"
            "    for c in (\"requests_total\", \"tokens_total\"):\n"
            "        reg.counter(c)\n"
            "    reg.gauge(\"depth\")\n"
            "    for cls in classes:\n"
            "        reg.histogram(f\"lat_s_class_{cls}\")\n")
        (pkg / "telemetry" / "journal.py").write_text(
            "EVENT_SCHEMAS = {\"thing_happened\": frozenset({\"x\"})}\n")
        # the real declared-metrics extractor also reads slo.py's
        # AlertEngine.__init__ declaring scope — provide an empty one
        (pkg / "telemetry" / "slo.py").write_text(
            "class AlertEngine:\n"
            "    def __init__(self):\n"
            "        pass\n")
        (pkg / "serving" / "app.py").write_text(app_src)
        return str(tmp_path)

    def test_clean_usage_passes(self, tmp_path):
        root = self._mini_repo(tmp_path, (
            "class App:\n"
            "    def ok(self, m, cls):\n"
            "        m.counter(\"requests_total\").inc()\n"
            "        m.histogram(f\"lat_s_class_{cls}\")\n"
            "        self.journal.emit(\"thing_happened\", x=1)\n"))
        assert check_declared_names(root) == []

    def test_seeded_mutations_caught(self, tmp_path):
        root = self._mini_repo(tmp_path, (
            "class App:\n"
            "    def bad(self, m):\n"
            "        m.counter(\"bogus_counter\").inc()\n"
            "        m.gauge(f\"depth_of_{self.x}\")\n"
            "        self.journal.emit(\"unknown_kind\", x=1)\n"))
        found = check_declared_names(root)
        ids = sorted(f.baseline_id for f in found)
        assert any("metric-name" in i and "bogus_counter" in i
                   for i in ids)
        assert any("metric-name" in i and "depth_of_" in i for i in ids)
        assert any("journal-kind" in i and "unknown_kind" in i
                   for i in ids)

    def test_module_level_and_nested_scopes_covered(self, tmp_path):
        """Post-review fix: metric/journal calls at MODULE scope (import
        -time registry wiring) and inside nested classes are audited
        too, not just top-level method bodies."""
        root = self._mini_repo(tmp_path, (
            "REG = object()\n"
            "REG.counter(\"module_scope_bogus\").inc()\n"
            "def outer():\n"
            "    class Inner:\n"
            "        def bad(self, m):\n"
            "            m.gauge(\"nested_scope_bogus\")\n"))
        ids = sorted(f.baseline_id for f in check_declared_names(root))
        assert any("module_scope_bogus" in i for i in ids)
        assert any("nested_scope_bogus" in i for i in ids)

    def test_journal_kind_param_propagation(self, tmp_path):
        root = self._mini_repo(tmp_path, (
            "class App:\n"
            "    def _record(self, action):\n"
            "        self.journal.emit(action, x=1)\n"
            "    def go(self):\n"
            "        self._record(\"thing_happened\")\n"
            "    def go_bad(self):\n"
            "        self._record(\"nope\")\n"))
        found = check_declared_names(root)
        ids = sorted(f.baseline_id for f in found)
        assert any("journal-kind" in i and ":nope" in i for i in ids)
        assert not any(":thing_happened" in i for i in ids)


# --------------------------------------------------------- whole repo
class TestWholeRepo:
    def test_repo_is_clean_modulo_baseline(self):
        active, suppressed = run_repo(REPO)
        assert active == [], "\n".join(f.render() for f in active)
        # the baseline is small and justified, not a dumping ground
        assert len(suppressed) <= 12

    # regression pins for the real findings this lint surfaced and
    # fixed (ISSUE 14 satellite): the ids must stay absent from the RAW
    # (un-baselined) findings — reintroducing the unlocked access would
    # resurface them and fail both this test and the tier-1 gate.
    FIXED_IDS = (
        "guarded-field:deepspeed_tpu/serving/queue.py:"
        "AdmissionQueue.set_preempt_pressure:_preempt_pressure",
        "guarded-field:deepspeed_tpu/serving/queue.py:"
        "AdmissionQueue.set_healthy_fraction:_proactive_frac",
        "guarded-field:deepspeed_tpu/serving/queue.py:"
        "AdmissionQueue.set_healthy_fraction:_healthy_frac",
        "guarded-field:deepspeed_tpu/telemetry/flight_recorder.py:"
        "FlightRecorder.maybe_snapshot:_last_snapshot_t",
    )

    def test_fixed_findings_stay_fixed(self):
        raw = analyze_repo(REPO)
        raw_ids = {f.baseline_id for f in raw}
        for fixed in self.FIXED_IDS:
            assert fixed not in raw_ids, fixed

    def test_fixed_finding_shapes_are_detectable(self):
        """The pre-fix code shapes, as fixtures: proves the whole-repo
        green isn't vacuous — the analyzer catches exactly what was
        fixed."""
        pre_fix_queue = """
import threading

class AdmissionQueue:
    _GUARDED_BY = {"_preempt_pressure": "_lock:writes",
                   "_healthy_frac": "_lock"}

    def __init__(self):
        self._lock = threading.Condition()
        self._preempt_pressure = False
        self._healthy_frac = 1.0

    def set_preempt_pressure(self, active):
        self._preempt_pressure = bool(active)     # the PR-11 shape

    def set_healthy_fraction(self, frac):
        with self._lock:
            self._healthy_frac = frac
        return round(self._healthy_frac, 4)       # re-read after release
"""
        ids = _ids(analyze_source(pre_fix_queue), "guarded-field")
        assert any("set_preempt_pressure:_preempt_pressure" in i
                   for i in ids)
        assert any("set_healthy_fraction:_healthy_frac" in i for i in ids)

    # behavioral regression: racing writers/readers over the fixed
    # fields — the journal transition must carry the fraction that
    # caused it and the flag write must not tear shed accounting
    def test_queue_pressure_flag_race(self):
        from deepspeed_tpu.serving.metrics import serving_metrics
        from deepspeed_tpu.serving.queue import AdmissionQueue
        from deepspeed_tpu.serving.request import Rejected, ServingRequest

        q = AdmissionQueue(2, serving_metrics(), brownout_threshold=0.0)
        stop = threading.Event()

        def flip():
            while not stop.is_set():
                q.set_preempt_pressure(True)
                q.set_preempt_pressure(False)

        t = threading.Thread(target=flip, daemon=True)
        t.start()
        try:
            shed = 0
            for i in range(200):
                req = ServingRequest([1, 2], 4, 1, None, None)
                try:
                    q.offer(req)
                except Rejected:
                    shed += 1
            assert shed == 198          # depth 2: everything else sheds
        finally:
            stop.set()
            t.join(1)

    def test_brownout_journal_fraction_consistent_under_race(self):
        from deepspeed_tpu.telemetry.journal import OpsJournal
        from deepspeed_tpu.serving.queue import AdmissionQueue

        journal = OpsJournal(capacity=4096)
        q = AdmissionQueue(8, None, brownout_threshold=0.5,
                           journal=journal)
        values = [0.1, 0.2, 0.3, 0.4, 0.9, 1.0]

        def hammer(seed):
            rng = np.random.default_rng(seed)
            for _ in range(200):
                q.set_healthy_fraction(float(rng.choice(values)))

        threads = [threading.Thread(target=hammer, args=(s,))
                   for s in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        # every enter/exit event must carry one of the fractions a
        # caller actually set — the pre-fix re-read-after-release could
        # smuggle a concurrent writer's value into the record
        for ev in journal.events():
            assert ev["detail"]["healthy_fraction"] in values


# ------------------------------------------------------ RankedLock unit
@pytest.fixture
def debug_state():
    state = enable_lock_debug(hold_threshold_s=60.0)
    try:
        yield state
    finally:
        disable_lock_debug()


class TestRankedLock:
    def test_undeclared_name_fails_fast(self):
        with pytest.raises(KeyError):
            RankedLock("no.such.lock")

    def test_order_enforced_in_debug_mode(self, debug_state):
        outer = RankedLock("serving.queue")        # 60
        inner = RankedLock("telemetry.tracer")     # 160
        with outer:
            with inner:                            # ascending: fine
                pass
        with pytest.raises(LockOrderError):
            with inner:
                with outer:                        # descending: violation
                    pass
        assert len(debug_state.violations) == 1
        v = debug_state.violations[0]
        assert v["lock"] == "serving.queue"
        assert v["holding"] == ["telemetry.tracer"]

    def test_self_deadlock_detected(self, debug_state):
        lock = RankedLock("serving.replica")
        with lock:
            with pytest.raises(LockOrderError, match="self-deadlock"):
                lock.acquire()

    def test_reentrant_reacquire_allowed(self, debug_state):
        rl = RankedLock("serving.router.membership", reentrant=True)
        with rl:
            with rl:
                pass
        assert debug_state.violations == []

    def test_condition_wait_and_notify(self, debug_state):
        cond = RankedCondition("serving.queue")
        hits = []

        def waiter():
            with cond:
                while not hits:
                    if not cond.wait(2.0):
                        return
            hits.append("woke")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            hits.append("set")
            cond.notify_all()
        t.join(3)
        assert hits == ["set", "woke"]
        assert debug_state.violations == []

    def test_hold_histogram_and_over_threshold(self):
        from deepspeed_tpu.serving.metrics import serving_metrics

        reg = serving_metrics()
        state = enable_lock_debug(metrics=reg, hold_threshold_s=0.02)
        try:
            lock = RankedLock("serving.handoff")
            with lock:
                pass
            with lock:
                time.sleep(0.05)            # over the 20ms threshold
        finally:
            disable_lock_debug()
        hist = reg.histogram("lock_hold_s")
        assert hist.count >= 2
        assert len(state.over_holds) == 1
        assert state.over_holds[0]["lock"] == "serving.handoff"
        assert state.over_holds[0]["hold_s"] >= 0.02

    def test_over_hold_of_recorders_own_lock_does_not_deadlock(self):
        """Post-review fix: hold-time side effects (including the
        over-hold flight-recorder dump, which takes the recorder's own
        ranked lock) must run AFTER the real release — an over-threshold
        hold of `telemetry.recorder` itself used to self-deadlock the
        releasing thread inside release()."""
        from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder
        from deepspeed_tpu.telemetry.tracer import Tracer

        recorder = FlightRecorder(Tracer(enabled=True))
        state = enable_lock_debug(recorder=recorder,
                                  hold_threshold_s=0.01)
        try:
            done = threading.Event()

            def hold_and_release():
                with recorder._lock:        # the recorder's OWN lock
                    time.sleep(0.05)        # over the 10ms threshold
                done.set()

            t = threading.Thread(target=hold_and_release, daemon=True)
            t.start()
            assert done.wait(5.0), \
                "release() deadlocked dumping its own over-hold"
            assert any(r["lock"] == "telemetry.recorder"
                       for r in state.over_holds)
        finally:
            disable_lock_debug()

    def test_maybe_snapshot_claims_watermark_atomically(self):
        """Post-review fix: the cadence check claims the watermark in
        the same locked section it reads it — a racer arriving before
        the (possibly slow) snapshot completes must skip."""
        from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder
        from deepspeed_tpu.telemetry.tracer import Tracer

        fr = FlightRecorder(Tracer(enabled=True))
        calls = []
        fr.snapshot_metrics = lambda: calls.append(1)   # never advances
        fr.maybe_snapshot(interval_s=60.0)
        fr.maybe_snapshot(interval_s=60.0)   # pre-fix: ran again
        assert calls == [1]

    def test_disabled_path_allocation_free(self):
        assert lock_debug() is None
        lock = RankedLock("serving.replica")
        with lock:                          # warm any lazy state
            pass
        here = __file__
        locks_file = RankedLock.acquire.__code__.co_filename
        tracemalloc.start()
        try:
            before = tracemalloc.take_snapshot()
            for _ in range(2000):
                with lock:
                    pass
                lock.acquire()
                lock.release()
            after = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        leaked = sum(
            st.count_diff for st in after.compare_to(before, "lineno")
            if st.traceback and st.traceback[0].filename in (here,
                                                             locks_file)
            and st.count_diff > 0)
        assert leaked <= 8, (
            f"disabled RankedLock leaked {leaked} objects over 4000 "
            "acquire/release cycles")


# ------------------------------------------------- declaration audits
class TestDeclarationAudits:
    def _used_rank_names(self):
        from deepspeed_tpu.analysis.concurrency import build_model

        model = build_model(REPO)
        used = set()
        for cm in model.classes:
            for decl in cm.locks.values():
                if decl.rank_name:
                    used.add(decl.rank_name)
            used.update(cm.rank_hints.values())
        return used

    def test_lock_ranks_and_constructions_agree_both_ways(self):
        used = self._used_rank_names()
        undeclared = used - set(LOCK_RANKS)
        assert not undeclared, f"locks constructed with undeclared " \
                               f"rank names: {sorted(undeclared)}"
        unused = set(LOCK_RANKS) - used
        assert not unused, f"LOCK_RANKS entries no lock uses: " \
                           f"{sorted(unused)}"

    def test_docs_rank_table_matches_lock_ranks_both_ways(self):
        path = os.path.join(REPO, "docs", "CONCURRENCY.md")
        doc = open(path).read()
        rows = dict(
            (m.group(2), int(m.group(1)))
            for m in re.finditer(r"^\| (\d+) \| `([\w.]+)` \|", doc,
                                 re.MULTILINE))
        assert rows == LOCK_RANKS, (
            "docs/CONCURRENCY.md rank table drifted from LOCK_RANKS:\n"
            f"doc-only: {sorted(set(rows) - set(LOCK_RANKS))}\n"
            f"code-only: {sorted(set(LOCK_RANKS) - set(rows))}\n"
            f"value diffs: "
            f"{ {k: (rows[k], LOCK_RANKS[k]) for k in rows if k in LOCK_RANKS and rows[k] != LOCK_RANKS[k]} }")

    def test_ranks_are_unique_and_runtime_matches_static(self):
        assert len(set(LOCK_RANKS.values())) == len(LOCK_RANKS)
        from deepspeed_tpu.analysis.concurrency import parse_lock_ranks

        assert parse_lock_ranks(REPO) == LOCK_RANKS


# --------------------------------------------------------------- chaos
VOCAB = 128
_model = None
_params = None


def _tiny_engine(i=0, kv_blocks=64, max_seqs=4):
    global _model, _params
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    if _model is None:
        _model = CausalLM(TransformerConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, max_seq_len=256, norm="rmsnorm",
            activation="silu", position="rope"))
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=kv_blocks, kv_block_size=8,
        max_tracked_sequences=32)
    eng = InferenceEngineV2(_model, params=_params, config=vcfg)
    _params = eng.params
    return eng


class TestChaosUnderLockDebug:
    def test_supervisor_kill_and_autoscaler_churn_clean(self):
        """ISSUE 14 satellite: one fault-injection chaos run (replica
        crash -> supervisor restart, plus autoscaler-path membership
        churn: grow + evacuating shrink) under RankedLock debug mode —
        no rank-order violations, no over-threshold holds. The hold
        threshold is generous (30s) so only a genuine wedge-while-locked
        could trip it on a loaded CI machine."""
        from deepspeed_tpu.serving import ServingConfig, ServingFrontend

        state = enable_lock_debug(hold_threshold_s=30.0,
                                  raise_on_violation=True)
        try:
            cfg = ServingConfig(
                max_queue_depth=64, default_max_new_tokens=4,
                fault_tolerance={"enabled": True,
                                 "restart_backoff_s": 0.05,
                                 "restart_backoff_max_s": 0.2,
                                 "supervisor_poll_s": 0.02,
                                 "max_retries": 3},
                faults={"enabled": True,
                        "schedule": [{"kind": "crash", "replica": 0,
                                      "at_step": 2}]})
            fe = ServingFrontend.from_engine_factory(
                _tiny_engine, cfg.model_copy(
                    update={"num_replicas": 2}))
            try:
                rng = np.random.default_rng(0)
                handles = [fe.submit(
                    rng.integers(0, VOCAB, size=int(n)).tolist(),
                    max_new_tokens=4)
                    for n in rng.integers(8, 20, size=10)]
                assert fe.wait_all(handles, timeout=180)
                # the injected crash actually fired and was survived
                assert fe.injector.fired_events()
                # membership churn: grow, then evacuating shrink
                rid = fe.add_replica()
                more = [fe.submit(
                    rng.integers(0, VOCAB, size=12).tolist(),
                    max_new_tokens=4) for _ in range(4)]
                assert fe.wait_all(more, timeout=120)
                fe.remove_replica(rid, timeout_s=30.0)
            finally:
                fe.shutdown(drain=False, timeout=10)
            assert state.violations == [], state.violations
            assert state.over_holds == [], state.over_holds
        finally:
            disable_lock_debug()
