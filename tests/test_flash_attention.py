"""Pallas flash-attention kernel tests (interpret mode on CPU).

Runs the same kernel code the TPU executes — forward with KV streamed
through the grid + saved LSE residuals, and the dq/dkv backward kernels —
against the pure-XLA grouped-attention reference, including GQA/MQA and
cross-length causal masking. Counterpart of the reference's kernel numeric
tests (tests/unit/ops/accelerators/test_accelerator_forward.py and
ds_transformer_cuda softmax/gemm checks).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import flash_attention as fa


@pytest.fixture(autouse=True)
def _force_interpret():
    old = fa._FORCE_INTERPRET
    fa._FORCE_INTERPRET = True
    yield
    fa._FORCE_INTERPRET = old


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


@pytest.mark.parametrize("H,KH", [(4, 4), (4, 2), (4, 1)])
@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(H, KH, causal):
    B, T, D = 2, 256, 64
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, KH, D), 1)
    v = _rand((B, T, KH, D), 2)
    out = fa.flash_attention(q, k, v, causal, 128, 128)
    ref = fa._attention_xla(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("H,KH", [(4, 4), (4, 2)])
def test_grads_match_reference(H, KH):
    B, T, D = 1, 256, 64
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, KH, D), 1)
    v = _rand((B, T, KH, D), 2)
    g = _rand((B, T, H, D), 3)

    def loss_pallas(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, True, 128, 128) * g)

    def loss_ref(q, k, v):
        return jnp.sum(fa._attention_xla(q, k, v, True) * g)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gp, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_cross_length_causal():
    """T != S (suffix-aligned causal, the KV-cache decode formulation)."""
    B, T, S, H, D = 1, 128, 256, 2, 64
    q = _rand((B, T, H, D), 0)
    k = _rand((B, S, H, D), 1)
    v = _rand((B, S, H, D), 2)
    out = fa.flash_attention(q, k, v, True, 128, 128)
    ref = fa._attention_xla(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_fallback_on_unaligned_shapes():
    """Non-128-multiple sequence lengths fall back to the XLA path."""
    B, T, H, D = 1, 100, 2, 64
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, H, D), 1)
    v = _rand((B, T, H, D), 2)
    out = fa.flash_attention(q, k, v, True)
    ref = fa._attention_xla(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_seq4096_grad_spot_check():
    """VERDICT r1 asked for a seq-4096 numeric grad check vs the XLA
    reference; run a thinned version in interpret mode (1 head) so CI stays
    fast, full-width on real TPU."""
    on_tpu = jax.devices()[0].platform == "tpu"
    B, T, H, D = 1, 4096, (4 if on_tpu else 1), 64
    q = _rand((B, T, H, D), 0, jnp.float32)
    k = _rand((B, T, H, D), 1, jnp.float32)
    v = _rand((B, T, H, D), 2, jnp.float32)
    g = _rand((B, T, H, D), 3, jnp.float32)

    def loss_pallas(q):
        return jnp.sum(fa.flash_attention(q, k, v, True, 512, 512) * g)

    def loss_ref(q):
        return jnp.sum(fa._attention_xla(q, k, v, True) * g)

    dq_p = jax.grad(loss_pallas)(q)
    dq_r = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(dq_p), np.asarray(dq_r),
                               rtol=5e-4, atol=5e-4)


def _dense_window_ref(q, k, v, window):
    """Brute-force dense sliding-window attention (independent of both the
    kernel and the XLA fallback — pins the Mistral window semantics:
    query p attends keys in (p − window, p])."""
    B, T, H, D = q.shape
    KH = k.shape[2]
    group = H // KH
    qg = np.asarray(q, np.float64).reshape(B, T, KH, group, D)
    kk = np.asarray(k, np.float64)
    vv = np.asarray(v, np.float64)
    s = np.einsum("btkgd,bskd->bkgts", qg, kk) / np.sqrt(D)
    qpos = np.arange(T)[:, None]
    kpos = np.arange(T)[None, :]
    keep = (qpos >= kpos) & (qpos - kpos < window)
    s = np.where(keep[None, None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bkgts,bskd->btkgd", p, vv)
    return o.reshape(B, T, H, D)


@pytest.mark.parametrize("H,KH", [(4, 4), (4, 2)])
@pytest.mark.parametrize("window", [64, 100, 256])
def test_sliding_window_forward(H, KH, window):
    """Windowed kernel vs the XLA fallback AND a brute-force dense
    reference (Mistral sliding-window semantics — reference parity:
    inference/v2/model_implementations/mistral/model.py:202)."""
    B, T, D = 2, 512, 64
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, KH, D), 1)
    v = _rand((B, T, KH, D), 2)
    out = fa.flash_attention(q, k, v, True, 128, 128, window)
    ref = fa._attention_xla(q, k, v, True, window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    dense = _dense_window_ref(q, k, v, window)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("window", [64, 100])
def test_sliding_window_grads(window):
    B, T, H, KH, D = 1, 512, 4, 2, 64
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, KH, D), 1)
    v = _rand((B, T, KH, D), 2)
    g = _rand((B, T, H, D), 3)

    def loss_pallas(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, True, 128, 128, window) * g)

    def loss_ref(q, k, v):
        return jnp.sum(fa._attention_xla(q, k, v, True, window) * g)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gp, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_sliding_window_cross_length():
    """Windowed decode-style attention with T != S (suffix-aligned)."""
    B, T, S, H, D = 1, 128, 512, 2, 64
    q = _rand((B, T, H, D), 0)
    k = _rand((B, S, H, D), 1)
    v = _rand((B, S, H, D), 2)
    out = fa.flash_attention(q, k, v, True, 128, 128, 100)
    ref = fa._attention_xla(q, k, v, True, 100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
