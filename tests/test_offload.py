"""Native-op + offload tests.

Reference counterparts: tests/unit/ops/adam/test_cpu_adam.py (C++ Adam vs
torch numeric parity), tests/unit/ops/aio/test_aio.py (async IO round
trip), tests/unit/runtime/zero/test_zero_offloadpp.py (Twin-Flow partial
offload training).
"""

import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.models import build_model
from deepspeed_tpu.ops.cpu_adam import (DeepSpeedCPUAdam, DeepSpeedCPUAdagrad,
                                        DeepSpeedCPULion)
from deepspeed_tpu.ops.op_builder import AsyncIOBuilder, CPUAdamBuilder
from deepspeed_tpu.runtime.swap_tensor import (AsyncTensorSwapper,
                                               OptimizerStateSwapper)


def test_native_ops_build():
    """The toolchain is baked into the image — native ops must compile."""
    assert CPUAdamBuilder().load() is not None
    assert AsyncIOBuilder().load() is not None


def test_cpu_adam_matches_torch():
    import torch

    rng = np.random.default_rng(0)
    p = rng.normal(size=1023).astype(np.float32)
    g = rng.normal(size=1023).astype(np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-2, weight_decay=0.1, adamw_mode=True)
    assert opt.has_native
    state = opt.init_state(p)
    params = p.copy()
    tp = torch.tensor(p.copy(), requires_grad=True)
    topt = torch.optim.AdamW([tp], lr=1e-2, weight_decay=0.1, eps=1e-8)
    for _ in range(4):
        opt.step(params, g, state)
        tp.grad = torch.tensor(g)
        topt.step()
    np.testing.assert_allclose(params, tp.detach().numpy(), rtol=3e-5, atol=3e-6)


def test_cpu_adam_native_matches_numpy():
    rng = np.random.default_rng(1)
    p = rng.normal(size=517).astype(np.float32)
    g = rng.normal(size=517).astype(np.float32)
    o1 = DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.01)
    o2 = DeepSpeedCPUAdam(lr=1e-3, weight_decay=0.01)
    o2._lib = None  # force numpy fallback
    p1, p2 = p.copy(), p.copy()
    s1, s2 = o1.init_state(p1), o2.init_state(p2)
    for _ in range(3):
        o1.step(p1, g, s1)
        o2.step(p2, g, s2)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-7)


def test_cpu_adagrad_and_lion_run():
    rng = np.random.default_rng(2)
    p = rng.normal(size=100).astype(np.float32)
    g = rng.normal(size=100).astype(np.float32)
    for opt in (DeepSpeedCPUAdagrad(lr=1e-2), DeepSpeedCPULion(lr=1e-3)):
        params = p.copy()
        state = opt.init_state(params)
        opt.step(params, g, state)
        assert np.isfinite(params).all()
        assert not np.allclose(params, p)


def test_sync_fallback_roundtrip_and_no_temp_left(tmp_path):
    """The no-native sync path (.tofile fallback) must round-trip and
    leave no .tmp droppings — the write goes temp + fsync + os.replace
    (the checkpointing.py atomic-write discipline)."""
    sw = AsyncTensorSwapper(str(tmp_path), n_threads=1)
    sw.close()                  # drops the native handle -> sync path
    arr = np.arange(257, dtype=np.float32)
    sw.swap_out("sync_key", arr)
    sw.wait()                   # no-op on the sync path
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    buf = np.empty_like(arr)
    sw.swap_in("sync_key", buf)
    np.testing.assert_array_equal(buf, arr)


def test_sync_fallback_write_is_atomic(tmp_path, monkeypatch):
    """A failed sync swap_out must never tear the destination: the old
    complete .swp survives (os.replace is the only publication step) and
    the temp file is cleaned up."""
    sw = AsyncTensorSwapper(str(tmp_path), n_threads=1)
    sw.close()
    old = np.full(64, 7.0, np.float32)
    sw.swap_out("k", old)
    new = np.full(64, 9.0, np.float32)
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("simulated crash at publication")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="simulated crash"):
        sw.swap_out("k", new)
    monkeypatch.setattr(os, "replace", real_replace)
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    buf = np.empty_like(old)
    sw.swap_in("k", buf)
    np.testing.assert_array_equal(buf, old)     # old content intact


def test_aio_roundtrip(tmp_path):
    sw = AsyncTensorSwapper(str(tmp_path), n_threads=2)
    assert sw.has_native
    rng = np.random.default_rng(3)
    arrays = {f"t{i}": rng.normal(size=1000 + i).astype(np.float32)
              for i in range(4)}
    for k, a in arrays.items():
        sw.swap_out(k, a)
    sw.wait()
    for k, a in arrays.items():
        buf = np.empty_like(a)
        sw.swap_in(k, buf)
        sw.wait()
        np.testing.assert_array_equal(buf, a)
    sw.close()


def test_aio_missing_file_reports_error(tmp_path):
    sw = AsyncTensorSwapper(str(tmp_path), n_threads=1)
    buf = np.empty(16, np.float32)
    sw.swap_in("never_written", buf)
    with pytest.raises(IOError):
        sw.wait()
    sw.close()


def test_optimizer_state_swapper(tmp_path):
    osw = OptimizerStateSwapper(str(tmp_path))
    osw.register("m", (64,))
    arr = osw.load("m")
    assert (arr == 0).all()
    arr[:] = 7.0
    osw.store("m", arr)
    again = osw.load("m")
    assert (again == 7.0).all()
    osw.close()


# ------------------------------------------------------------- engine tiers
def _train(engine, steps=5):
    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(
        0, 256, size=(engine.train_batch_size(), 33), dtype=np.int64)}
    losses = []
    for _ in range(steps):
        loss = engine(data)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def _cfg(offload: dict):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2, "offload_optimizer": offload},
        "mesh": {"data": -1, "fsdp": 2},
        "steps_per_print": 100,
    }


def test_zero_offload_cpu_trains():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=_cfg({"device": "cpu"}))
    assert engine._offload_plan is not None
    assert len(engine._offload_plan.offloaded) > 0
    losses = _train(engine, 6)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_zero_offload_partial_ratio():
    """Twin-Flow (ZeRO-Offload++): ratio<1 keeps some leaves on device."""
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config=_cfg({"device": "cpu", "ratio": 0.5}))
    plan = engine._offload_plan
    assert 0 < len(plan.offloaded) < len(plan.offloaded) + len(plan.kept)
    assert len(plan.kept) > 0
    losses = _train(engine, 5)
    assert losses[-1] < losses[0]


def test_zero_offload_nvme_trains(tmp_path):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config=_cfg({"device": "nvme", "nvme_path": str(tmp_path)}))
    assert engine._offload_plan.swapper is not None
    losses = _train(engine, 4)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # moments actually live on disk
    assert any(f.endswith(".swp") for f in os.listdir(tmp_path))


def test_offload_matches_device_update():
    """CPU-offloaded AdamW must track the on-device update closely."""
    cfg_dev = _cfg({"device": "none"})
    cfg_off = _cfg({"device": "cpu"})
    import deepspeed_tpu.parallel.topology as topo

    e1, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg_dev)
    l1 = _train(e1, 4)
    topo.reset_topology()
    e2, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg_off)
    l2 = _train(e2, 4)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-4)


def test_offload_checkpoint_roundtrip(tmp_path):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=_cfg({"device": "cpu"}))
    _train(engine, 3)
    engine.save_checkpoint(str(tmp_path))

    import deepspeed_tpu.parallel.topology as topo

    topo.reset_topology()
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=_cfg({"device": "cpu"}))
    engine2.load_checkpoint(str(tmp_path))
    for i in engine._offload_plan.offloaded:
        np.testing.assert_array_equal(engine._offload_plan.masters[i],
                                      engine2._offload_plan.masters[i])
