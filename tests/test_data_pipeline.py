"""Data efficiency pipeline tests (reference
tests/unit/runtime/test_data_efficiency.py): curriculum schedules, the
curriculum sampler's difficulty gating, the analyzer's map-reduce output,
random-LTD gather/scatter + gradients, and engine seqlen curriculum."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import build_model
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler, DataAnalyzer, DeepSpeedDataSampler,
    RandomLTDScheduler, apply_random_ltd, sample_token_indices)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import metric_seqlen


# ---------------------------------------------------------------- scheduler
def test_fixed_linear_schedule():
    s = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    assert s.get_difficulty(0) == 8
    assert s.get_difficulty(50) == (8 + 28) // 8 * 8   # quantized midpoint
    assert s.get_difficulty(100) == 64
    assert s.get_difficulty(10**6) == 64


def test_fixed_root_grows_faster_early():
    lin = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 512,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 1000,
                            "difficulty_step": 8}})
    root = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 512,
        "schedule_type": "fixed_root",
        "schedule_config": {"total_curriculum_step": 1000,
                            "difficulty_step": 8, "root_degree": 2}})
    assert root.get_difficulty(100) > lin.get_difficulty(100)
    assert root.get_difficulty(1000) == lin.get_difficulty(1000) == 512


def test_fixed_discrete_schedule():
    s = CurriculumScheduler({
        "min_difficulty": 1, "max_difficulty": 3,
        "schedule_type": "fixed_discrete",
        "schedule_config": {"difficulty": [1, 2, 3], "max_step": [5, 10]}})
    assert s.get_difficulty(3) == 1
    assert s.get_difficulty(7) == 2
    assert s.get_difficulty(11) == 3


def test_custom_schedule():
    s = CurriculumScheduler({
        "min_difficulty": 1, "max_difficulty": 100,
        "schedule_type": "custom"})
    s.set_custom_get_difficulty(lambda step: step * 2)
    assert s.get_difficulty(21) == 42


# ------------------------------------------------------------------ sampler
def sampler_config(enabled=True):
    return {
        "seed": 7,
        "data_sampling": {
            "num_epochs": 4,
            "curriculum_learning": {
                "enabled": enabled,
                "metrics": {
                    "seqlen": {
                        "min_difficulty": 4, "max_difficulty": 64,
                        "schedule_type": "fixed_linear",
                        "difficulty_type": "value",
                        "schedule_config": {"total_curriculum_step": 10,
                                            "difficulty_step": 4},
                    }
                },
            },
        },
    }


def test_sampler_gates_by_difficulty():
    lengths = np.arange(1, 101)           # sample i has "seqlen" i+1
    sampler = DeepSpeedDataSampler(
        sampler_config(), one_epoch_total_samples=100, micro_batch_size=4,
        data_parallel_size=2, gradient_accumulation_steps=1,
        metric_values={"seqlen": lengths})
    it = iter(sampler)
    first = next(it)                       # step 1: difficulty near min (4)
    assert first.shape == (8,)
    assert lengths[first].max() <= 8
    for _ in range(12):                    # run past total_curriculum_step
        batch = next(it)
    assert lengths[batch].max() > 8        # pool opened up


def test_sampler_resume_deterministic():
    lengths = np.arange(1, 101)
    mk = lambda: DeepSpeedDataSampler(      # noqa: E731
        sampler_config(), 100, 4, 2, 1, metric_values={"seqlen": lengths})
    a = mk()
    it_a = iter(a)
    batches = [next(it_a) for _ in range(5)]
    state = a.state_dict()

    b = mk()
    b.load_state_dict(state)
    cont_a = next(it_a)
    cont_b = next(iter(b))
    np.testing.assert_array_equal(cont_a, cont_b)


# ----------------------------------------------------------------- analyzer
def test_analyzer_map_reduce(tmp_path):
    data = [{"input_ids": np.zeros(int(n), dtype=np.int64)}
            for n in [5, 3, 9, 1, 7, 2]]
    for wid in range(2):
        DataAnalyzer(data, {"seqlen": metric_seqlen}, str(tmp_path),
                     num_workers=2, worker_id=wid).run_map()
    out = DataAnalyzer(data, {"seqlen": metric_seqlen}, str(tmp_path),
                       num_workers=2, worker_id=0).run_reduce()
    values = np.load(out["seqlen"]["values"])
    np.testing.assert_array_equal(values, [5, 3, 9, 1, 7, 2])
    order = np.load(out["seqlen"]["index_by_value"])
    np.testing.assert_array_equal(values[order], sorted(values))


# --------------------------------------------------------------- random-LTD
def test_random_ltd_gather_scatter_identity():
    rng = jax.random.PRNGKey(0)
    x = jnp.arange(2 * 8 * 4, dtype=jnp.float32).reshape(2, 8, 4)
    idx = sample_token_indices(rng, num_layers=3, batch=2, seq=8, reserved=5)
    assert idx.shape == (3, 2, 5)
    # sorted ascending, unique per row
    assert bool((jnp.diff(idx, axis=-1) > 0).all())
    out = apply_random_ltd(lambda part: part, x, idx[0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_random_ltd_layer_only_touches_sampled_tokens():
    rng = jax.random.PRNGKey(1)
    x = jnp.ones((2, 8, 4))
    idx = sample_token_indices(rng, 1, 2, 8, reserved=3)[0]
    out = apply_random_ltd(lambda p: p * 10.0, x, idx)
    touched = np.zeros((2, 8), dtype=bool)
    for b in range(2):
        touched[b, np.asarray(idx[b])] = True
    np.testing.assert_allclose(np.asarray(out)[touched], 10.0)
    np.testing.assert_allclose(np.asarray(out)[~touched], 1.0)


def test_random_ltd_gradients_flow():
    rng = jax.random.PRNGKey(2)
    idx = sample_token_indices(rng, 1, 1, 6, reserved=3)[0]
    w = jnp.ones((4, 4))

    def loss(w, x):
        return apply_random_ltd(lambda p: p @ w, x, idx).sum()

    g = jax.grad(loss)(w, jnp.ones((1, 6, 4)))
    assert np.isfinite(np.asarray(g)).all() and np.abs(g).sum() > 0


def test_random_ltd_scheduler():
    s = RandomLTDScheduler({
        "random_ltd_schedule": {
            "min_value": 16, "max_value": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"seq_per_step": 16, "require_steps": 10}}})
    assert s.update_seq(0) == 16
    assert s.update_seq(10) == 32
    assert s.update_seq(1000) == 64
    st = s.state_dict()
    s2 = RandomLTDScheduler({"min_value": 16, "max_value": 64})
    s2.load_state_dict(st)
    assert s2.get_current_seq() == 64


# ----------------------------------------------------------- engine wiring
def test_engine_seqlen_curriculum(devices8):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1, "fsdp": 1},
        "steps_per_print": 10**9,
        "curriculum_learning": {
            "enabled": True,
            "min_difficulty": 8, "max_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 8},
        },
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                               config=cfg)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, size=(16, 33),
                                       dtype=np.int64)}
    for _ in range(6):
        loss = engine.train_batch(itertools.repeat(batch))
        assert np.isfinite(float(loss))
    # schedule exhausted: difficulty at max (= full 32-token sequence)
    assert engine.curriculum_scheduler.get_current_difficulty() == 32


def test_engine_deepspeed_io_with_curriculum_sampler(devices8):
    """deepspeed_io(data_sampler=...): the loader draws difficulty-gated
    index batches from the curriculum sampler (reference engine.py
    deepspeed_io + data_pipeline sampler integration)."""
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1, "fsdp": 1},
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                               config=cfg)
    n = 64
    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(0, 256, size=(n, 33),
                                      dtype=np.int64)}
    lengths = np.arange(1, n + 1)     # sample i has difficulty i+1
    sampler = DeepSpeedDataSampler(
        sampler_config(), one_epoch_total_samples=n,
        micro_batch_size=2,
        data_parallel_size=engine.topology.get_data_parallel_world_size(),
        metric_values={"seqlen": lengths})
    loader = engine.deepspeed_io(data, data_sampler=iter(sampler))
    it = iter(loader)
    batch = next(it)
    assert batch["input_ids"].shape[0] == 16    # global micro batch
    loss = engine.train_batch(iter([batch]))
    assert np.isfinite(float(loss))


def test_loader_len_with_sampler(devices8):
    n = 64
    lengths = np.arange(1, n + 1)
    sampler = DeepSpeedDataSampler(
        sampler_config(), one_epoch_total_samples=n, micro_batch_size=2,
        data_parallel_size=8, gradient_accumulation_steps=2,
        metric_values={"seqlen": lengths})
    from deepspeed_tpu.runtime.dataloader import DeepSpeedTpuDataLoader

    data = {"input_ids": np.zeros((n, 33), np.int64)}
    loader = DeepSpeedTpuDataLoader(data, batch_size=16,
                                    data_sampler=sampler)
    # total samples = 64*num_epochs(4) = 256; the loader slices each
    # sampler yield (global batch 32, incl. gas=2) into 16-wide global
    # micro batches -> 16 yields
    assert len(loader) == 256 // 16
    it = iter(loader)
    first = next(it)
    assert first["input_ids"].shape[0] == 16   # one global MICRO batch
    with pytest.raises(TypeError, match="no length"):
        len(DeepSpeedTpuDataLoader(data, batch_size=16,
                                   data_sampler=iter(sampler)))
