"""ZeRO-Infinity parameter streaming: model bigger than the device budget.

Mirrors the reference's swap-tensor tests (tests/unit/runtime/zero/
test_zero_nvme_offload.py pattern): params live off-device, stream through
in layer groups, training converges, and I/O counters prove streaming."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import TINY_TEST, CausalLM
from deepspeed_tpu.parallel import topology as topo


CFG = dataclasses.replace(TINY_TEST, num_layers=8, tie_embeddings=False,
                          num_kv_heads=4)


def make_engine(tmp_path, device="nvme", group_layers=2, gas=1):
    topo.reset_topology()
    from deepspeed_tpu.runtime.config import load_config
    from deepspeed_tpu.runtime.zero_infinity import ZeroInfinityEngine

    config = load_config({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": device,
                              "nvme_path": str(tmp_path / "swap")},
        },
        "steps_per_print": 10**9,
    })
    return ZeroInfinityEngine(CausalLM(CFG), config,
                              group_layers=group_layers)


def batch():
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 256, size=(4, 33), dtype=np.int64)}


def test_initialize_selects_streaming_engine(tmp_path):
    topo.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(CFG),
        config={
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 3,
                "offload_param": {"device": "nvme",
                                  "nvme_path": str(tmp_path / "swap")}},
        })
    from deepspeed_tpu.runtime.zero_infinity import ZeroInfinityEngine

    assert isinstance(engine, ZeroInfinityEngine)
    engine.close()


def test_streaming_forward_matches_monolithic(tmp_path):
    """The streamed group-by-group forward == one whole-model loss."""
    engine = make_engine(tmp_path, group_layers=3)   # uneven split: 3+3+2
    data = batch()

    # assemble the full param tree from the store
    layers = {}
    for k in engine._layer_keys:
        parts = [engine.store.get(f"layers.{k}.g{gi}")
                 for gi in range(len(engine.groups))]
        layers[k] = jnp.asarray(np.concatenate(parts, axis=0))
    edges = jax.tree.map(jnp.asarray, engine.gather_edges())
    params = {"embed": edges["embed"], "layers": layers,
              "final_norm": edges["final_norm"],
              "lm_head": edges["lm_head"]}
    model = CausalLM(CFG)
    mono = float(model.loss(params, data))

    reads_before = engine.store.reads
    streamed = engine.train_batch(dict(data))
    assert engine.store.reads > reads_before, "no streaming reads happened"
    np.testing.assert_allclose(streamed, mono, rtol=1e-5)
    engine.close()


@pytest.mark.parametrize("device", ["cpu", "nvme"])
def test_streaming_training_converges(tmp_path, device):
    engine = make_engine(tmp_path, device=device)
    data = batch()
    losses = [engine.train_batch(dict(data)) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.5, f"no convergence: {losses}"
    if device == "nvme":
        # reads: params+moments per group per step; writes prove page-out
        assert engine.store.reads > len(engine.groups) * 8
        assert engine.store.writes > len(engine.groups) * 8
    engine.close()


def test_device_budget_accounting(tmp_path):
    """Full param bytes exceed what any single step keeps on device: the
    resident set is O(2 groups + edges), not O(model)."""
    engine = make_engine(tmp_path, group_layers=2)
    edge_bytes = sum(v.nbytes for grp in engine.gather_edges().values()
                     for v in grp.values())
    group_bytes = (engine.param_bytes - edge_bytes) // len(engine.groups)
    # r5: edges stream too — the resident set during a sweep is two layer
    # groups + the edge device copies, strictly below the full model
    resident_budget = 2 * group_bytes + edge_bytes
    assert engine.param_bytes > resident_budget, (
        "model must exceed the streaming resident set for the test to mean "
        "anything")
    assert len(engine.groups) == 4
    engine.close()


# ---------------------------------------------------------------- mesh × streaming

def make_mesh_engine(tmp_path, data=2, fsdp=4, group_layers=2,
                     device="cpu"):
    """Streaming engine over a data×fsdp mesh (round-4: the reference's
    NVMe swap runs under ZeRO-3 partitioning — stage3.py:72 +
    partitioned_param_swapper.py:36 page per-rank shards)."""
    topo.reset_topology()
    from deepspeed_tpu.runtime.config import load_config
    from deepspeed_tpu.runtime.zero_infinity import ZeroInfinityEngine

    t = topo.MeshTopology.build(data=data, fsdp=fsdp)
    config = load_config({
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": device,
                              "nvme_path": str(tmp_path / "swap")},
        },
        "steps_per_print": 10**9,
    })
    return ZeroInfinityEngine(CausalLM(CFG), config,
                              group_layers=group_layers, mesh=t.mesh)


def test_mesh_streaming_loss_matches_single_device(tmp_path):
    """fsdp×data-sharded streaming reproduces the single-device streaming
    loss trajectory (same seeded host init)."""
    single = make_engine(tmp_path / "a", device="cpu")
    meshed = make_mesh_engine(tmp_path / "b", data=2, fsdp=4)
    data = batch()
    for step in range(3):
        ls = single.train_batch(dict(data))
        lm = meshed.train_batch(dict(data))
        np.testing.assert_allclose(lm, ls, rtol=2e-4,
                                   err_msg=f"step {step}")
    single.close()
    meshed.close()


def test_mesh_streaming_pages_per_shard(tmp_path):
    """I/O counters prove per-shard paging: every sharded leaf is read as
    fsdp-many 1/F-sized pieces, never as a whole leaf."""
    engine = make_mesh_engine(tmp_path, data=2, fsdp=4)
    bytes_before = engine.store.bytes_read
    engine.train_batch(batch())
    step_bytes = engine.store.bytes_read - bytes_before
    # fwd pages params once, bwd pages params + both moments once
    assert step_bytes > 0
    assert step_bytes <= 4.5 * engine.param_bytes, (
        "paging volume should be ~4x param bytes per step (1 fwd + 1 bwd "
        f"read of params + m + v), got {step_bytes / engine.param_bytes:.1f}x")
    shard_keys = [k for k in engine.store.read_keys
                  if k.startswith("layers.") and ".s" in k]
    assert shard_keys, "no per-shard reads recorded"
    # all fsdp shard indices show up
    sis = {int(k.rsplit(".s", 1)[1]) for k in shard_keys}
    assert sis == {0, 1, 2, 3}, sis
    # sharded leaves are never read whole: for every leaf with a shard
    # axis, no un-suffixed key was read
    for k in engine._layer_keys:
        if engine._shard_axis[k] is not None:
            for gi in range(len(engine.groups)):
                assert f"layers.{k}.g{gi}" not in engine.store.read_keys
    # each piece is 1/F of the leaf
    some_key = next(k for k in engine._layer_keys
                    if engine._shard_axis[k] is not None)
    piece = engine.store.get(engine._key(some_key, 0, 0))
    whole_elems = np.prod(
        jax.eval_shape(engine.module.init, jax.random.PRNGKey(0))
        ["layers"][some_key].shape[1:])
    assert piece.size == (engine.groups[0].stop - engine.groups[0].start) \
        * whole_elems // 4
    engine.close()


def test_mesh_streaming_converges(tmp_path):
    engine = make_mesh_engine(tmp_path, data=2, fsdp=4)
    data = batch()
    losses = [engine.train_batch(dict(data)) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.5, f"no convergence: {losses}"
    engine.close()


def test_streaming_report_quantifies_overhead(tmp_path):
    """streaming_report pins the streaming-vs-resident trade: paging volume
    per step ~4x param bytes (fwd + bwd params + both moments) and the
    8/6 recompute FLOPs factor of the grouped-vjp backward."""
    engine = make_engine(tmp_path, device="cpu")
    engine.train_batch(batch())
    engine.train_batch(batch())
    rep = engine.streaming_report()
    assert rep["groups"] == 4 and rep["param_bytes"] > 0
    assert abs(rep["recompute_flops_factor"] - 8 / 6) < 1e-9
    # measured paging volume tracks the analytic expectation
    assert rep["bytes_read_per_step"] <= 1.2 * rep["expected_bytes_per_step"]
    assert rep["bytes_read_per_step"] >= 0.5 * rep["expected_bytes_per_step"]
    engine.close()


def test_gradient_accumulation_matches_big_batch(tmp_path):
    """GAS=2 over two micro batches reproduces the GAS=1 trajectory on
    their concatenation exactly (mean-of-micro-grads == big-batch grad
    for equal micro sizes) — the r4 'no gradient accumulation' constraint
    is gone."""
    rng = np.random.default_rng(7)
    b1 = {"input_ids": rng.integers(0, 256, size=(4, 33), dtype=np.int64)}
    b2 = {"input_ids": rng.integers(0, 256, size=(4, 33), dtype=np.int64)}
    big = {"input_ids": np.concatenate([b1["input_ids"],
                                        b2["input_ids"]])}
    acc = make_engine(tmp_path / "acc", device="cpu", gas=2)
    ref = make_engine(tmp_path / "ref", device="cpu", gas=1)
    for step in range(3):
        la = acc.train_batch(iter([dict(b1), dict(b2)]))
        lr_ = ref.train_batch(dict(big))
        np.testing.assert_allclose(la, lr_, rtol=2e-4,
                                   err_msg=f"step {step}")
    # accumulation buffers paged through the store, not host RAM
    assert any(k.startswith("acc.") for k in acc.store._mem
               ) or acc.store.swapper is not None
    acc.close()
    ref.close()


def test_gas_requires_iterator(tmp_path):
    engine = make_engine(tmp_path, device="cpu", gas=2)
    with pytest.raises(TypeError, match="iterator"):
        engine.train_batch(batch())
    engine.close()


def test_edges_stream_through_store(tmp_path):
    """r4 held embed/final_norm/lm_head resident (replicated fp32 + dense
    host Adam each step); r5 streams them through the store like layer
    groups. I/O counters prove it: wte/lm_head page as per-fsdp-shard
    pieces (never whole), and their optimizer moments live on the store
    too. Reference: partitioned_param_swapper.py:36 — everything swaps,
    not just blocks."""
    engine = make_mesh_engine(tmp_path, data=2, fsdp=4)
    engine.train_batch(batch())
    edge_reads = [k for k in engine.store.read_keys
                  if k.startswith("edge.")]
    assert any(k.startswith("edge.embed.wte.s") for k in edge_reads)
    assert any(k.startswith("edge.lm_head.w.s") for k in edge_reads)
    # sharded edge leaves are never read whole
    assert "edge.embed.wte" not in engine.store.read_keys
    assert "edge.lm_head.w" not in engine.store.read_keys
    sis = {int(k.rsplit(".s", 1)[1]) for k in edge_reads
           if k.startswith("edge.embed.wte.s")}
    assert sis == {0, 1, 2, 3}, sis
    # edge optimizer moments page through the store as well
    assert any(k.startswith("opt_m.edge.") for k in engine.store.read_keys)
    engine.close()


def test_gas_on_mesh_converges(tmp_path):
    """GAS + fsdp×data mesh + streamed edges all compose."""
    topo.reset_topology()
    from deepspeed_tpu.runtime.config import load_config
    from deepspeed_tpu.runtime.zero_infinity import ZeroInfinityEngine

    t = topo.MeshTopology.build(data=2, fsdp=4)
    config = load_config({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu",
                              "nvme_path": str(tmp_path / "swap")}},
        "steps_per_print": 10**9,
    })
    engine = ZeroInfinityEngine(CausalLM(CFG), config, group_layers=2,
                                mesh=t.mesh)
    data = batch()
    losses = [engine.train_batch(iter([dict(data), dict(data)]))
              for _ in range(6)]
    assert losses[-1] < losses[0] - 0.3, f"no convergence: {losses}"
    engine.close()


# ------------------------------------------------- async host-store batching
class _RecordingSwapper:
    """Fake aio engine that models asynchrony honestly: a swap_out is
    only readable after wait() commits it — so a get() that skipped the
    read-after-write flush would blow up, and the counters prove how
    many waits the store actually paid."""

    def __init__(self):
        self.in_flight = {}
        self.committed = {}
        self.write_calls = 0
        self.wait_calls = 0

    def swap_out(self, key, array):
        self.write_calls += 1
        self.in_flight[key] = array          # NOT copied: aio reads the
        #                                      caller's memory at wait time

    def swap_in(self, key, array):
        if key not in self.committed:
            raise IOError(f"read of uncommitted key {key!r} — a write "
                          "was not waited on before the read")
        array[...] = self.committed[key]

    def wait(self):
        self.wait_calls += 1
        for k, a in self.in_flight.items():
            self.committed[k] = np.array(a, copy=True)
        self.in_flight.clear()

    def close(self):
        pass


def test_host_store_put_batches_waits(tmp_path):
    """The ISSUE 10 satellite: ``_HostStore.put`` must NOT wait per
    write (that serializes every NVMe write with compute) — writes stay
    in flight, buffers stay alive, and ONE flush() at the group boundary
    settles them all."""
    from deepspeed_tpu.runtime.zero_infinity import _HostStore

    store = _HostStore("nvme", str(tmp_path / "swap"), 1)
    store.swapper.close()
    store._read_swapper.close()
    fake = _RecordingSwapper()
    store.swapper = fake
    store._read_swapper = fake
    arrs = [np.full(32, float(i), np.float32) for i in range(3)]
    for i, a in enumerate(arrs):
        store.put(f"k{i}", a)
    # three writes dispatched, ZERO waits paid — they overlap compute
    assert fake.write_calls == 3
    assert fake.wait_calls == 0
    assert len(store._pending) == 3          # buffers kept alive
    store.flush()
    assert fake.wait_calls == 1              # one wait for the batch
    assert not store._pending
    got = store.get("k1")
    np.testing.assert_array_equal(got, arrs[1])


def test_host_store_get_flushes_pending_write(tmp_path):
    """Read-after-write inside a group: get() of a key with an in-flight
    swap_out must flush first (the file is not complete until the wait)
    — the fake swapper raises if the store ever skips that."""
    from deepspeed_tpu.runtime.zero_infinity import _HostStore

    store = _HostStore("nvme", str(tmp_path / "swap"), 1)
    store.swapper.close()
    store._read_swapper.close()
    fake = _RecordingSwapper()
    store.swapper = fake
    store._read_swapper = fake
    arr = np.arange(16, dtype=np.float32)
    store.put("acc.x", arr)
    assert fake.wait_calls == 0
    got = store.get("acc.x")                 # would raise without flush
    np.testing.assert_array_equal(got, arr)
    assert fake.wait_calls >= 1


def test_host_store_reads_do_not_drain_in_flight_writes(tmp_path):
    """Reads use their OWN aio handle: a get() of a non-pending key must
    not wait on in-flight writes (a shared handle's wait() would drain
    them, re-serializing exactly what the group-boundary batching
    overlapped)."""
    from deepspeed_tpu.runtime.zero_infinity import _HostStore

    store = _HostStore("nvme", str(tmp_path / "swap"), 1)
    store.swapper.close()
    store._read_swapper.close()
    writes, reads = _RecordingSwapper(), _RecordingSwapper()
    reads.committed = writes.committed      # same files on disk
    store.swapper, store._read_swapper = writes, reads
    a = np.full(8, 1.0, np.float32)
    store.put("a", a)
    store.flush()                           # "a" durable
    b = np.full(8, 2.0, np.float32)
    store.put("b", b)                       # in flight on the write handle
    got = store.get("a")                    # non-pending key
    np.testing.assert_array_equal(got, a)
    assert writes.wait_calls == 1           # the read drained NOTHING
    assert len(store._pending) == 1         # "b" still overlapping
    store.flush()
    assert writes.wait_calls == 2
