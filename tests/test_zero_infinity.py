"""ZeRO-Infinity parameter streaming: model bigger than the device budget.

Mirrors the reference's swap-tensor tests (tests/unit/runtime/zero/
test_zero_nvme_offload.py pattern): params live off-device, stream through
in layer groups, training converges, and I/O counters prove streaming."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import TINY_TEST, CausalLM
from deepspeed_tpu.parallel import topology as topo


CFG = dataclasses.replace(TINY_TEST, num_layers=8, tie_embeddings=False,
                          num_kv_heads=4)


def make_engine(tmp_path, device="nvme", group_layers=2):
    topo.reset_topology()
    from deepspeed_tpu.runtime.config import load_config
    from deepspeed_tpu.runtime.zero_infinity import ZeroInfinityEngine

    config = load_config({
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": device,
                              "nvme_path": str(tmp_path / "swap")},
        },
        "steps_per_print": 10**9,
    })
    return ZeroInfinityEngine(CausalLM(CFG), config,
                              group_layers=group_layers)


def batch():
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 256, size=(4, 33), dtype=np.int64)}


def test_initialize_selects_streaming_engine(tmp_path):
    topo.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(CFG),
        config={
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 3,
                "offload_param": {"device": "nvme",
                                  "nvme_path": str(tmp_path / "swap")}},
        })
    from deepspeed_tpu.runtime.zero_infinity import ZeroInfinityEngine

    assert isinstance(engine, ZeroInfinityEngine)
    engine.close()


def test_streaming_forward_matches_monolithic(tmp_path):
    """The streamed group-by-group forward == one whole-model loss."""
    engine = make_engine(tmp_path, group_layers=3)   # uneven split: 3+3+2
    data = batch()

    # assemble the full param tree from the store
    layers = {}
    for k in engine._layer_keys:
        parts = [engine.store.get(f"layers.{k}.g{gi}")
                 for gi in range(len(engine.groups))]
        layers[k] = jnp.asarray(np.concatenate(parts, axis=0))
    params = {"embed": dict(engine._edge_params["embed"]),
              "layers": layers,
              "final_norm": dict(engine._edge_params["final_norm"]),
              "lm_head": dict(engine._edge_params["lm_head"])}
    model = CausalLM(CFG)
    mono = float(model.loss(params, data))

    reads_before = engine.store.reads
    streamed = engine.train_batch(dict(data))
    assert engine.store.reads > reads_before, "no streaming reads happened"
    np.testing.assert_allclose(streamed, mono, rtol=1e-5)
    engine.close()


@pytest.mark.parametrize("device", ["cpu", "nvme"])
def test_streaming_training_converges(tmp_path, device):
    engine = make_engine(tmp_path, device=device)
    data = batch()
    losses = [engine.train_batch(dict(data)) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.5, f"no convergence: {losses}"
    if device == "nvme":
        # reads: params+moments per group per step; writes prove page-out
        assert engine.store.reads > len(engine.groups) * 8
        assert engine.store.writes > len(engine.groups) * 8
    engine.close()


def test_device_budget_accounting(tmp_path):
    """Full param bytes exceed what any single step keeps on device: the
    resident set is O(2 groups + edges), not O(model)."""
    engine = make_engine(tmp_path, group_layers=2)
    group_bytes = engine.param_bytes // len(engine.groups)
    edge_bytes = sum(int(np.prod(v.shape)) * 4
                     for grp in engine._edge_params.values()
                     for v in grp.values())
    resident_budget = 2 * group_bytes + edge_bytes
    assert engine.param_bytes + edge_bytes > resident_budget, (
        "model must exceed the streaming resident set for the test to mean "
        "anything")
    assert len(engine.groups) == 4
    engine.close()
