"""Fleet-wide KV locality tests (ISSUE 17, docs/SERVING.md "Fleet KV
locality").

Four layers:

- **Hash/digest layer**: ``chain_hashes`` must agree with the chain the
  engine's prefix index actually holds (``record_tokens``), and the
  digest/export/import trio must round-trip KV *content* — a warmed
  replica has to produce byte-identical greedy tokens, not just index
  hits.
- **Scoring layer**: ``AffinityState.choose`` unit tests — leading-run
  overlap credit, load-vs-credit arbitration, the share cap, and the
  None fallback that keeps the caller's cache-blind pick reachable.
- **Router layer**: the pick path hashes the prompt ONCE per pick at
  fleet size 16, the ``req=None`` free-slot probe never hashes, and a
  router without affinity is the historical least-loaded pick.
- **Policy/frontend layer**: predictive scaling grows strictly earlier
  than the watermark baseline (reason ``predicted_pressure``) without
  adding flapping, shrink never acts on a forecast, grow-path warm-up
  populates the new replica (journal + histogram + digest), and the
  disabled path is the historical stack — no AffinityState, no
  predicted signal, same greedy tokens.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import deepspeed_tpu.serving.affinity as affinity_mod
from deepspeed_tpu.inference.v2.engine_v2 import (
    InferenceEngineV2, RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.testing import greedy_generate
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving import (AutoscalerConfig, ServingConfig,
                                   ServingFrontend, serving_metrics)
from deepspeed_tpu.serving.affinity import AffinityState, chain_hashes
from deepspeed_tpu.serving.autoscaler import (FleetController, FleetSignals,
                                              ReplicaInfo)
from deepspeed_tpu.serving.config import AffinityConfig
from deepspeed_tpu.serving.queue import AdmissionQueue
from deepspeed_tpu.serving.replica import ReplicaState
from deepspeed_tpu.serving.request import ServingRequest
from deepspeed_tpu.serving.router import ReplicaRouter

VOCAB = 128
BS = 8          # kv block size used throughout

_model = None
_params = None


def tiny_engine(i=0, **cfg_over):
    global _model, _params
    import jax

    if _model is None:
        _model = CausalLM(TransformerConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, max_seq_len=256, norm="rmsnorm",
            activation="silu", position="rope"))
        _params = _model.init(jax.random.PRNGKey(0))
    base = dict(max_ragged_batch_size=128, max_ragged_sequence_count=4,
                max_chunk_tokens=32, kv_blocks=64, kv_block_size=BS,
                max_tracked_sequences=32, enable_prefix_cache=True)
    base.update(cfg_over)
    return InferenceEngineV2(_model, params=_params,
                             config=RaggedInferenceEngineConfig(**base))


def prompts_shared(n, seed, shared_len=24, tail=6):
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, VOCAB, size=shared_len).tolist()
    return shared, [shared + rng.integers(0, VOCAB, size=tail).tolist()
                    for _ in range(n)]


# ================================================== hash / digest layer
class TestChainHashes:
    def test_agrees_with_recorded_index(self):
        """Every full-block chain hash of a served prompt must appear in
        the engine's digest — the router predicts cache hits from the
        prompt alone, so disagreement here silently zeroes all credit."""
        eng = tiny_engine()
        p = list(range(30))
        greedy_generate(eng, [p], max_new_tokens=4)
        digest = set(eng.prefix_digest())
        want = chain_hashes(p, BS)
        assert want, "prompt long enough for full blocks"
        assert set(want) <= digest

    def test_last_token_excluded_like_match_walk(self):
        # 16 tokens, block 8: the match walk caps at len-1=15, so only
        # the first block is hashable — exactly one chain entry
        assert len(chain_hashes(list(range(16)), BS)) == 1
        assert len(chain_hashes(list(range(17)), BS)) == 2

    def test_short_prompt_has_no_hashes(self):
        assert chain_hashes(list(range(BS)), BS) == []
        assert chain_hashes([], BS) == []

    def test_digest_bounded_and_off_when_cache_off(self):
        eng = tiny_engine()
        ps = [list(range(i, i + 20)) for i in range(6)]
        greedy_generate(eng, ps, max_new_tokens=4)
        assert len(eng.prefix_digest(max_entries=3)) == 3
        cold = tiny_engine(enable_prefix_cache=False)
        assert cold.prefix_digest() == []


class TestWarmupRoundTrip:
    def test_export_import_preserves_tokens(self):
        """The content test: a replica warmed from a donor's exported
        blocks must (a) report the donor's chain hashes in its digest,
        (b) take prefix-cache hits on the donor's traffic, and (c) still
        produce byte-identical greedy tokens — imported KV is real KV,
        not just index entries."""
        shared, ps = prompts_shared(3, seed=11)
        donor = tiny_engine()
        ref = greedy_generate(donor, ps, max_new_tokens=5)

        entries = donor.export_prefix_blocks(max_blocks=32)
        assert entries, "donor exported nothing"

        warmed = tiny_engine()
        assert warmed.prefix_digest() == []
        n = warmed.import_prefix_blocks(entries)
        assert n == len(entries)
        assert set(warmed.prefix_digest()) >= {
            hash(key) for key, _ in entries}

        got = greedy_generate(warmed, ps, max_new_tokens=5, uid_base=100)
        assert got == ref, "warmed replica broke greedy parity"
        assert warmed.prefix_stats()["tokens_saved"] > 0, \
            "warm-up produced no first-request prefix hits"

    def test_import_respects_budget_and_dedup(self):
        donor = tiny_engine()
        _, ps = prompts_shared(2, seed=12)
        greedy_generate(donor, ps, max_new_tokens=4)
        entries = donor.export_prefix_blocks(max_blocks=32)
        warmed = tiny_engine()
        n = warmed.import_prefix_blocks(entries)
        assert n == len(entries)
        assert warmed.import_prefix_blocks(entries) == 0  # all dedup'd
        cold = tiny_engine(enable_prefix_cache=False)
        assert cold.import_prefix_blocks(entries) == 0    # cache off


# ======================================================= scoring layer
def _rep(rid, load=0):
    return SimpleNamespace(replica_id=rid, outstanding_tokens=load)


def _cost(r):
    return (r.outstanding_tokens, r.replica_id)


def _aff(**over):
    base = dict(enabled=True, share_window=8, max_share=0.5,
                refresh_interval_s=1e-9)
    base.update(over)
    return AffinityState(AffinityConfig(**base))


def _req(tokens):
    return ServingRequest(list(tokens), max_new_tokens=4, priority=1,
                          deadline_s=None, eos_token_id=None)


class TestAffinityChoose:
    def test_steers_to_warm_replica_and_counts_tokens(self):
        aff = _aff()
        p = list(range(24))
        hashes = chain_hashes(p, BS)
        r0, r1 = _rep(0), _rep(1)
        aff._digests = {1: frozenset(hashes)}
        best = aff.choose(_req(p), [r0, r1], _cost, BS)
        assert best is r1
        st = aff.stats()
        assert st["hits"] == 1
        assert st["tokens_saved"] == len(hashes) * BS

    def test_no_digest_anywhere_falls_back_none(self):
        aff = _aff()
        assert aff.choose(_req(range(24)), [_rep(0), _rep(1)],
                          _cost, BS) is None
        assert aff.stats()["misses"] == 1

    def test_short_prompt_falls_back_none(self):
        aff = _aff()
        aff._digests = {0: frozenset([1, 2, 3])}
        assert aff.choose(_req(range(BS)), [_rep(0)], _cost, BS) is None

    def test_leading_run_only_no_credit_for_trailing_hits(self):
        # digest holds every hash EXCEPT the first block's: the match
        # walk would stop immediately, so affinity must score zero
        aff = _aff()
        p = list(range(33))
        hashes = chain_hashes(p, BS)
        assert len(hashes) >= 3
        aff._digests = {1: frozenset(hashes[1:])}
        assert aff.choose(_req(p), [_rep(0), _rep(1)], _cost, BS) is None

    def test_load_overrules_small_credit(self):
        # one warm block (8 tokens credit) vs 1000 outstanding tokens:
        # the load term wins and the fleet counts it a miss
        aff = _aff()
        p = list(range(12))
        hashes = chain_hashes(p, BS)
        r0, r1 = _rep(0, load=0), _rep(1, load=1000)
        aff._digests = {1: frozenset(hashes)}
        best = aff.choose(_req(p), [r0, r1], _cost, BS)
        assert best is r0
        assert aff.stats() == {"hits": 0, "misses": 1, "tokens_saved": 0}

    def test_share_cap_diverts_to_second_warmest(self):
        aff = _aff(share_window=8, max_share=0.5)
        p = list(range(24))
        hashes = chain_hashes(p, BS)
        # r1 fully warm, r2 warm for one block, equal load
        aff._digests = {1: frozenset(hashes), 2: frozenset(hashes[:1])}
        reps = [_rep(0), _rep(1), _rep(2)]
        picks = [aff.choose(_req(p), reps, _cost, BS).replica_id
                 for _ in range(8)]
        # r1 takes wins until it owns max_share of the window capacity
        # (4 of 8), then credit zeroes and r2's single block wins
        assert picks[:4] == [1, 1, 1, 1]
        assert set(picks[4:]) == {2}
        counts = aff.share_counts()
        cap = aff.cfg.max_share * aff._recent.maxlen
        assert all(c <= cap for c in counts.values()), counts

    def test_digestless_candidate_is_cache_blind_not_error(self):
        aff = _aff()
        p = list(range(24))
        aff._digests = {1: frozenset(chain_hashes(p, BS))}
        # replica 0 has no digest entry at all: zero credit, no raise
        best = aff.choose(_req(p), [_rep(0), _rep(1)], _cost, BS)
        assert best.replica_id == 1

    def test_refresh_tolerates_sick_replicas(self):
        aff = _aff()

        class Sick:
            replica_id = 0

            def prefix_digest(self, n):
                raise RuntimeError("transport down")

        warm = SimpleNamespace(
            replica_id=1, prefix_digest=lambda n: frozenset([7, 8]))
        bare = SimpleNamespace(replica_id=2)     # no digest surface
        aff.refresh([Sick(), warm, bare], now=1.0)
        assert aff.digest_of(0) == frozenset()
        assert aff.digest_of(1) == frozenset([7, 8])
        assert aff.digest_of(2) == frozenset()


# ========================================================= router layer
class _FakeReplica:
    """Just enough surface for ReplicaRouter.pick: healthy, accepting,
    with a settable load and digest."""

    def __init__(self, rid, load=0, digest=()):
        self.replica_id = rid
        self.model_id = "default"
        self.role = "mixed"
        self.engine = SimpleNamespace(
            config=SimpleNamespace(kv_block_size=BS))
        self.state = ReplicaState.HEALTHY
        self.outstanding_tokens = load
        self.outstanding_prefill_tokens = load
        self.outstanding_decode_tokens = 0
        self.accepting = True
        self.has_capacity = True
        self._digest = frozenset(digest)

    def check_health(self):
        return ReplicaState.HEALTHY

    def prefix_digest(self, max_entries=512):
        return self._digest


def _router(reps, affinity=None):
    return ReplicaRouter(reps, AdmissionQueue(64), affinity=affinity)


class TestRouterPickPath:
    def test_one_hash_pass_per_pick_fleet16(self, monkeypatch):
        """Micro-benchmark of the satellite claim: at fleet size 16 the
        pick path runs exactly ONE chain-hash pass per pick — overlap
        scoring against all 16 digests reuses the memoized hashes."""
        p = list(range(40))
        hashes = chain_hashes(p, BS)
        reps = [_FakeReplica(i, digest=hashes[:1 + i % 3])
                for i in range(16)]
        aff = _aff(share_window=64)
        aff.refresh(reps, now=1.0)
        router = _router(reps, affinity=aff)

        calls = {"n": 0}
        real = affinity_mod.chain_hashes

        def counting(tokens, bs):
            calls["n"] += 1
            return real(tokens, bs)

        monkeypatch.setattr(affinity_mod, "chain_hashes", counting)
        for k in range(10):
            calls["n"] = 0
            assert router.pick(_req(p)) is not None
            assert calls["n"] == 1, f"pick {k} hashed {calls['n']} times"

    def test_free_slot_probe_never_hashes(self, monkeypatch):
        reps = [_FakeReplica(i) for i in range(4)]
        aff = _aff()
        router = _router(reps, affinity=aff)
        calls = {"n": 0}

        def counting(tokens, bs):
            calls["n"] += 1
            return []

        monkeypatch.setattr(affinity_mod, "chain_hashes", counting)
        assert router.pick() is not None            # the _loop probe shape
        assert calls["n"] == 0

    def test_affinity_none_is_least_loaded_pick(self):
        """The disabled path: no AffinityState means pick is the
        historical min-cost selection, even when replicas would have
        had digest overlap."""
        p = list(range(24))
        reps = [_FakeReplica(0, load=10, digest=chain_hashes(p, BS)),
                _FakeReplica(1, load=0)]
        router = _router(reps, affinity=None)
        assert router.pick(_req(p)).replica_id == 1

    def test_affinity_beats_load_tie_and_respects_fallback(self):
        p = list(range(24))
        warm = chain_hashes(p, BS)
        reps = [_FakeReplica(0, load=5), _FakeReplica(1, load=5,
                                                      digest=warm)]
        aff = _aff()
        aff.refresh(reps, now=1.0)
        router = _router(reps, affinity=aff)
        assert router.pick(_req(p)).replica_id == 1
        # a prompt with no hashable prefix falls through to least-loaded
        assert router.pick(_req(range(4))).replica_id == 0


# ================================================ predictive scaling
class _PredictiveFleet:
    """Minimal actuation surface: a mixed fleet whose signals carry a
    settable actual queue depth and predicted depth."""

    def __init__(self, n=1):
        self.n = n
        self.queue_depth = 0
        self.predicted = None
        self.actions = []

    def fleet_signals(self):
        infos = tuple(ReplicaInfo(i, "mixed", True, False, 0, 0)
                      for i in range(self.n))
        return FleetSignals(queue_depth=self.queue_depth, replicas=infos,
                            predicted_queue_depth=self.predicted)

    def add_replica(self, role):
        self.n += 1
        self.actions.append(("add", role))
        return self.n - 1

    def remove_replica(self, rid, reason="scale_down"):
        self.n -= 1
        self.actions.append(("remove", rid, reason))
        return True

    def set_replica_role(self, rid, role):
        return True

    def set_proactive_brownout(self, frac):
        pass


def _controller(fleet, **cfg):
    base = dict(enabled=True, min_replicas=1, max_replicas=4,
                scale_up_queue_per_replica=4.0,
                scale_down_queue_per_replica=0.25,
                scale_down_tokens_per_replica=8.0,
                up_stable_ticks=2, down_stable_ticks=3,
                scale_up_cooldown_s=5.0, scale_down_cooldown_s=10.0,
                tick_interval_s=1.0)
    base.update(cfg)
    return FleetController(AutoscalerConfig(**base), fleet,
                           async_actions=False)


class TestPredictiveScaling:
    def _ramp(self, fleet, ctl, predictive):
        """A load ramp: actual depth crosses the watermark (4/replica)
        only at t=6, but the trend projection sees it from t=0."""
        t = 0.0
        depths = [1, 2, 2, 3, 3, 4, 6, 8, 10, 12]
        first_up = None
        for d in depths:
            fleet.queue_depth = d
            fleet.predicted = 8.0 if predictive else None
            ctl.tick(t)
            if first_up is None and fleet.actions:
                first_up = t
            t += 1.0
        return first_up

    def test_predictive_grows_strictly_earlier_than_watermark(self):
        base_fleet = _PredictiveFleet()
        base_t = self._ramp(base_fleet, _controller(base_fleet),
                            predictive=False)
        pred_fleet = _PredictiveFleet()
        pred_ctl = _controller(pred_fleet)
        pred_t = self._ramp(pred_fleet, pred_ctl, predictive=True)
        assert base_t is not None and pred_t is not None
        assert pred_t < base_t, (pred_t, base_t)
        ups = [d for d in pred_ctl.decision_log
               if d["action"] == "scale_up"]
        assert ups[0]["reason"] == "predicted_pressure"

    def test_watermark_grow_keeps_historical_reason(self):
        fleet = _PredictiveFleet()
        ctl = _controller(fleet)
        fleet.queue_depth = 50          # actual pressure, prediction too
        fleet.predicted = 60.0
        ctl.tick(0.0)
        ctl.tick(1.0)
        ups = [d for d in ctl.decision_log if d["action"] == "scale_up"]
        assert ups and ups[0]["reason"] == "queue_pressure"

    def test_prediction_none_is_watermark_byte_for_byte(self):
        a, b = _PredictiveFleet(), _PredictiveFleet()
        ca, cb = _controller(a), _controller(b)
        for t, d in enumerate([1, 3, 5, 6, 2, 1, 0, 0, 0, 0, 0, 0]):
            a.queue_depth = b.queue_depth = d
            a.predicted = None          # affinity off
            b.predicted = None
            ca.tick(float(t))
            cb.tick(float(t))
        assert a.actions == b.actions
        strip = lambda log: [{k: v for k, v in d.items() if k != "t"}
                             for d in log]
        assert strip(ca.decision_log) == strip(cb.decision_log)

    def test_forecast_never_shrinks_and_never_flaps(self):
        """A spiky prediction over calm actuals may grow (that is its
        job) but must never cause a shrink, and a predicted grow must
        not be immediately reverted (no add->remove->add churn)."""
        fleet = _PredictiveFleet(n=2)
        ctl = _controller(fleet, min_replicas=1)
        t = 0.0
        for step in range(20):
            fleet.queue_depth = 1       # calm actuals, never down_cond
            fleet.predicted = 12.0 if step in (2, 3) else None
            ctl.tick(t)
            t += 1.0
        kinds = [a[0] for a in fleet.actions]
        assert "remove" not in kinds, fleet.actions
        assert kinds.count("add") <= 1
        # and a LOW forecast over genuinely idle actuals still shrinks
        # on the actual watermark only — prediction adds no down force
        for step in range(8):
            fleet.queue_depth = 0
            fleet.predicted = 0.0
            ctl.tick(t)
            t += 1.0
        downs = [d for d in ctl.decision_log
                 if d["action"] == "scale_down"]
        assert all(d["reason"] == "idle" for d in downs)


# ================================================ frontend integration
def _serving_cfg(enabled=True, **aff_over):
    aff = dict(enabled=enabled, refresh_interval_s=0.05,
               warmup_enabled=True, warmup_max_blocks=16)
    aff.update(aff_over)
    return ServingConfig(num_replicas=2, max_queue_depth=64, affinity=aff)


def _run(fe, ps, max_new=4):
    hs = [fe.submit(p, max_new_tokens=max_new) for p in ps]
    assert fe.wait_all(hs, timeout=300), [h.state for h in hs]
    return [[ev.token for ev in h.drain()] for h in hs]


class TestFrontendIntegration:
    def test_disabled_builds_none_of_it(self):
        fe = ServingFrontend.from_engine_factory(tiny_engine,
                                                 _serving_cfg(enabled=False))
        try:
            assert fe._affinity is None
            assert fe.router.affinity is None
            assert fe.fleet_signals().predicted_queue_depth is None
            _, ps = prompts_shared(3, seed=5)
            assert all(len(g) for g in _run(fe, ps))
            snap = fe.metrics.snapshot()
            assert snap.get("router_affinity_hits", 0) == 0
            assert not [e for e in fe.journal.events()
                        if e.get("kind") == "replica_warmup"]
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_enabled_parity_and_hits(self):
        """Affinity changes placement, never tokens: enabled vs disabled
        fleets produce identical greedy streams, and the enabled fleet
        accrues digest-steered hits on shared-prefix traffic."""
        _, ps = prompts_shared(8, seed=6)
        fe_off = ServingFrontend.from_engine_factory(
            tiny_engine, _serving_cfg(enabled=False))
        try:
            ref = _run(fe_off, ps)
        finally:
            fe_off.shutdown(drain=False, timeout=5)

        fe = ServingFrontend.from_engine_factory(tiny_engine,
                                                 _serving_cfg())
        try:
            got = _run(fe, ps)
            assert got == ref, "affinity broke greedy parity"
            time.sleep(0.3)             # a router tick refreshes digests
            got2 = _run(fe, ps)
            assert got2 == ref
            st = fe._affinity.stats()
            assert st["hits"] > 0 and st["tokens_saved"] > 0, st
            snap = fe.metrics.snapshot()
            assert snap["router_affinity_hits"] == st["hits"]
            assert snap["prefix_tokens_saved_fleet"] == st["tokens_saved"]
            cap = (fe.config.affinity.max_share
                   * fe._affinity._recent.maxlen)
            assert all(c <= cap
                       for c in fe._affinity.share_counts().values())
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_add_replica_warms_from_fleet(self):
        fe = ServingFrontend.from_engine_factory(tiny_engine,
                                                 _serving_cfg())
        try:
            _, ps = prompts_shared(6, seed=7)
            _run(fe, ps)
            rid = fe.add_replica()
            evs = [e for e in fe.journal.events()
                   if e.get("kind") == "replica_warmup"]
            assert evs, "no replica_warmup journal event"
            d = evs[-1]["detail"]
            assert d["replica"] == rid and d["blocks"] > 0
            assert d["warmup_s"] >= 0
            new_rep = next(r for r in fe.router.replicas
                           if r.replica_id == rid)
            assert len(new_rep.prefix_digest()) > 0, \
                "warm-up left the grown replica cold"
            snap = fe.metrics.snapshot()
            assert snap["replica_warmup_s"]["count"] >= 1
            assert snap["replicas_warming"] == 0   # inc/dec balanced
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_warmup_off_leaves_grown_replica_cold(self):
        fe = ServingFrontend.from_engine_factory(
            tiny_engine, _serving_cfg(warmup_enabled=False))
        try:
            _, ps = prompts_shared(4, seed=8)
            _run(fe, ps)
            rid = fe.add_replica()
            assert not [e for e in fe.journal.events()
                        if e.get("kind") == "replica_warmup"]
            new_rep = next(r for r in fe.router.replicas
                           if r.replica_id == rid)
            assert new_rep.prefix_digest() == frozenset()
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_predicted_signal_tracks_submission_trend(self):
        fe = ServingFrontend.from_engine_factory(tiny_engine,
                                                 _serving_cfg())
        try:
            _, ps = prompts_shared(6, seed=9)
            _run(fe, ps)
            sig = fe.fleet_signals()
            assert sig.predicted_queue_depth is not None
            assert sig.predicted_queue_depth >= 0
            assert fe.metrics.snapshot()["predicted_load"] >= 0
        finally:
            fe.shutdown(drain=False, timeout=5)
