"""Test harness: 8 virtual CPU devices standing in for a TPU slice.

Counterpart of the reference's DistributedTest harness
(tests/unit/common.py:102): the reference forks N processes with real
NCCL/Gloo loopback; the TPU-native equivalent is a single process with
``--xla_force_host_platform_device_count=8`` — real XLA collectives over a
virtual 8-device mesh, exercising the same SPMD programs that run on ICI.
"""

import os

# Must happen before the first device query. The axon TPU plugin (if present)
# pins jax_platforms at interpreter startup, so override via jax.config too.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_topology():
    """Each test builds its own mesh topology."""
    from deepspeed_tpu.parallel import topology

    topology.reset_topology()
    yield
    topology.reset_topology()


@pytest.fixture
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


def pytest_sessionfinish(session, exitstatus):
    """Teardown-hygiene tripwire (VERDICT r3 weak #7: the interpreter
    lingered ~10 min after [100%]): name any non-daemon thread still alive
    so a slow exit is attributable instead of mysterious."""
    import sys
    import threading

    stragglers = [t for t in threading.enumerate()
                  if t is not threading.main_thread() and not t.daemon]
    if stragglers:
        print(f"\n[conftest] non-daemon threads alive at session finish "
              f"(interpreter exit will join them): "
              f"{[t.name for t in stragglers]}", file=sys.stderr)
