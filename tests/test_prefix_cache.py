"""Prefix-cache KV block reuse (ISSUE 2): refcounted BlockedAllocator,
hash-chain block index in DSStateManager, scheduler tail-only prefill, LRU
eviction, and cancel/deadline-expiry while blocks are shared. The
cache-off engine must behave exactly like the pre-cache engine."""

import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.ragged import BlockedAllocator, DSStateManager
from deepspeed_tpu.inference.v2.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.inference.v2.testing import (assert_greedy_parity,
                                                greedy_generate)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

VOCAB = 128
BS = 8          # kv block size used throughout


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=2,
                            max_seq_len=128, norm="rmsnorm",
                            activation="silu", position="rope")
    model = CausalLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def make_engine(model, params, enabled=True, kv_blocks=64, max_cached=None,
                max_seqs=4):
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=kv_blocks, kv_block_size=BS,
        max_tracked_sequences=64, enable_prefix_cache=enabled,
        prefix_cache_max_blocks=max_cached)
    return InferenceEngineV2(model, params=params, config=vcfg)


def model_cfg():
    return TransformerConfig(vocab_size=VOCAB, hidden_size=16,
                             intermediate_size=32, num_layers=1, num_heads=2,
                             max_seq_len=256, norm="rmsnorm",
                             activation="silu", position="rope")


def tiny_manager(enabled=True, num_blocks=16, max_cached=None):
    return DSStateManager(model_cfg(), 32, num_blocks, BS,
                          enable_prefix_cache=enabled,
                          prefix_cache_max_blocks=max_cached)


# -------------------------------------------------------- allocator refcounts
def test_allocator_share_release_refcounts():
    a = BlockedAllocator(8)
    blocks = a.allocate(2)
    assert all(a.ref_count(b) == 1 for b in blocks)
    a.share(blocks)
    assert all(a.ref_count(b) == 2 for b in blocks)
    assert a.release(blocks) == []          # still referenced: nothing freed
    assert a.free_blocks == 6
    assert a.release(blocks) == blocks      # last reference: back to pool
    assert a.free_blocks == 8
    assert all(a.ref_count(b) == 0 for b in blocks)


def test_allocator_release_validates_atomically():
    a = BlockedAllocator(8)
    b = a.allocate(1)[0]
    with pytest.raises(ValueError, match="invalid or double free"):
        a.release([b, b])                   # one ref, two releases
    # failed call must not have mutated anything
    assert a.ref_count(b) == 1
    assert a.free_blocks == 7
    a.share([b])
    a.release([b, b])                       # two refs, two releases: fine
    assert a.free_blocks == 8


def test_allocator_free_keeps_double_free_error():
    a = BlockedAllocator(10)
    blocks = a.allocate(2)
    a.free(blocks)
    with pytest.raises(ValueError, match="invalid or double free"):
        a.free(blocks)
    with pytest.raises(ValueError, match="invalid or double free"):
        a.free([99])
    with pytest.raises(ValueError):
        a.share([5])                        # free block cannot be shared


# ------------------------------------------------------ manager hash index
def _fill_sequence(mgr, uid, tokens):
    seq = mgr.get_or_create_sequence(uid)
    mgr.maybe_allocate_kv(seq, len(tokens))
    seq.seen_tokens += len(tokens)
    mgr.record_tokens(seq, tokens)
    return seq


def test_match_shares_full_blocks_only():
    mgr = tiny_manager()
    toks = list(range(20))                  # 2 full blocks + partial
    seq = _fill_sequence(mgr, 1, toks)
    donor_blocks = list(seq.kv_blocks[:2])
    mgr.flush_sequence(1)
    # cached full blocks survive the flush, partial block went free
    assert mgr.free_blocks == 16 - 2
    assert mgr.available_blocks == 16
    matched = mgr.match_prefix(2, toks + [7, 7])
    assert matched == 16                    # the two full blocks
    seq2 = mgr.get_sequence(2)
    assert seq2.kv_blocks == donor_blocks   # the same device blocks
    assert seq2.seen_tokens == 16
    assert all(mgr.allocator.ref_count(b) == 2 for b in donor_blocks)
    assert mgr.prefix_stats()["tokens_saved"] == 16


def test_last_token_never_served_from_cache():
    """An exact-multiple prompt still leaves >= 1 token to prefill (the
    forward that produces first-token logits)."""
    mgr = tiny_manager()
    toks = list(range(16))                  # exactly 2 blocks
    _fill_sequence(mgr, 1, toks)
    assert mgr.match_prefix(2, toks) == BS  # only the first block matches


def test_disabled_cache_is_inert():
    mgr = tiny_manager(enabled=False)
    assert mgr.match_prefix(5, list(range(40))) == 0
    assert mgr.get_sequence(5) is None      # no sequence state created
    seq = _fill_sequence(mgr, 1, list(range(20)))
    assert seq.hashed_blocks == 0           # record_tokens no-ops
    mgr.flush_sequence(1)
    assert mgr.free_blocks == 16            # nothing retained
    assert mgr.available_blocks == mgr.free_blocks


def test_lru_eviction_under_pool_pressure():
    mgr = tiny_manager(num_blocks=8)
    _fill_sequence(mgr, 1, list(range(16)))         # 2 cached after flush
    mgr.flush_sequence(1)
    _fill_sequence(mgr, 2, list(range(100, 116)))   # 2 more, newer
    mgr.flush_sequence(2)
    assert mgr.free_blocks == 4 and mgr.available_blocks == 8
    # allocating 6 must evict LRU cached blocks instead of failing
    seq = mgr.get_or_create_sequence(3)
    mgr.maybe_allocate_kv(seq, 6 * BS)
    assert len(seq.kv_blocks) == 6
    st = mgr.prefix_stats()
    assert st["evictions"] == 2
    # LRU order: uid 1's older prefix was evicted, uid 2's survives
    assert mgr.match_prefix(4, list(range(100, 116)) + [0]) == 16
    assert mgr.match_prefix(5, list(range(16)) + [0]) == 0


def test_max_cached_blocks_cap():
    mgr = tiny_manager(num_blocks=16, max_cached=2)
    _fill_sequence(mgr, 1, list(range(32)))         # 4 full blocks
    evicted_or_skipped = mgr.prefix_stats()
    assert len(mgr._index) <= 2
    # in-use blocks are never evicted: everything still referenced by uid 1
    assert evicted_or_skipped["evictions"] == 0
    mgr.flush_sequence(1)
    assert mgr.available_blocks == 16


def test_referenced_blocks_never_evicted():
    mgr = tiny_manager(num_blocks=4)
    seq = _fill_sequence(mgr, 1, list(range(16)))   # holds 2 cached blocks
    matched = mgr.match_prefix(2, list(range(16)) + [9])
    assert matched == 16
    # uid 1 and uid 2 both reference the cached blocks; pool has 2 free
    with pytest.raises(ValueError):
        mgr.maybe_allocate_kv(mgr.get_or_create_sequence(3), 3 * BS)
    for b in seq.kv_blocks[:2]:
        assert mgr.allocator.ref_count(b) == 3      # cache + two sequences


# ------------------------------------------------- scheduler integration
def _run_batch(engine, prompts, uid_base, max_new=4, cancel_uid=None,
               cancel_after_steps=1):
    sched = ContinuousBatchingScheduler(engine)
    for i, p in enumerate(prompts):
        sched.submit(uid_base + i, p, max_new_tokens=max_new)
    steps = 0
    while sched.has_work and steps < 500:
        sched.step()
        steps += 1
        if cancel_uid is not None and steps == cancel_after_steps:
            sched.cancel(cancel_uid)
    return sched


def test_generated_tokens_identical_cache_on_off(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(0)
    sys_p = rng.integers(0, VOCAB, size=24).tolist()
    prompts = [sys_p + rng.integers(0, VOCAB, size=6).tolist()
               for _ in range(4)]
    outs = {}
    for enabled in (False, True):
        engine = make_engine(model, params, enabled=enabled)
        # sequential (the default): the cache warms in submission order
        outs[enabled] = greedy_generate(engine, prompts, uid_base=100,
                                        max_new_tokens=5)
        if enabled:
            st = engine.prefix_stats()
            assert st["hits"] >= 3 * 3           # requests 1..3 hit sys blocks
            assert st["tokens_saved"] >= 3 * 24
    assert_greedy_parity(outs[False], outs[True], "prefix cache")


def test_cancel_under_prefix_sharing(model_and_params):
    """Cancelling one sharer must not free blocks the other still reads;
    the pool is whole again (free + cached) once every request finished."""
    model, params = model_and_params
    rng = np.random.default_rng(1)
    sys_p = rng.integers(0, VOCAB, size=24).tolist()
    engine = make_engine(model, params, enabled=True)
    # donor populates the cache
    _run_batch(engine, [sys_p + [1, 2]], 300)
    mgr = engine.state_manager
    cached = dict(mgr._index)
    assert cached, "donor registered no blocks"
    # two sharers in flight; cancel one mid-generation
    sched = _run_batch(engine, [sys_p + [3, 4], sys_p + [5, 6]], 310,
                       max_new=8, cancel_uid=310, cancel_after_steps=2)
    assert sched.finished[310].finish_reason == "cancelled"
    assert sched.finished[311].finish_reason in ("length", "eos")
    assert len(sched.finished[311].generated) == 8  # survivor unharmed
    # every cached block is back to exactly one (cache-held) reference
    for b in mgr._index.values():
        assert mgr.allocator.ref_count(b) == 1
    assert mgr.available_blocks == engine.config.kv_blocks
    st = engine.prefix_stats()
    assert st["tokens_saved"] >= 2 * 16           # both sharers matched


def test_failed_forward_registers_nothing(model_and_params):
    """A put() whose forward raises must leave no sequence-state commit
    and no index entry — otherwise a later prompt could match blocks
    whose KV was never written."""
    model, params = model_and_params
    engine = make_engine(model, params, enabled=True)
    uid, toks = 500, list(range(20))

    def boom(*a, **k):
        raise RuntimeError("transient device error")

    real_forward = engine.paged.forward
    engine.paged.forward = boom
    with pytest.raises(RuntimeError):
        engine.put([uid], [toks])
    seq = engine.state_manager.get_sequence(uid)
    assert seq.seen_tokens == 0              # retryable: nothing committed
    assert not engine.state_manager._index   # nothing matchable
    engine.paged.forward = real_forward
    engine.put([uid], [toks])                # retry succeeds and commits
    assert engine.state_manager.get_sequence(uid).seen_tokens == 20
    assert len(engine.state_manager._index) == 2


def test_evictable_counter_matches_recount(model_and_params):
    """The incremental evictable counter the admission path reads must
    equal a full recount after a mixed share/flush/evict workload."""
    model, params = model_and_params
    engine = make_engine(model, params, enabled=True, kv_blocks=24)
    rng = np.random.default_rng(3)
    sys_p = rng.integers(0, VOCAB, size=24).tolist()
    for i in range(5):
        _run_batch(engine, [sys_p + rng.integers(0, VOCAB, size=4).tolist()],
                   600 + 10 * i, max_new=6)
    mgr = engine.state_manager
    recount = sum(1 for b in mgr._index.values()
                  if mgr.allocator.ref_count(b) == 1)
    assert mgr._evictable == recount
    assert mgr.available_blocks == mgr.free_blocks + recount


def test_serving_config_enables_engine_cache(model_and_params):
    """`serving: {prefix_cache: {enabled: true}}` must actually turn the
    cache on for every replica engine (the config-driven path)."""
    from deepspeed_tpu.serving import (PrefixCacheConfig, ServingConfig,
                                       ServingFrontend)

    model, params = model_and_params
    engine = make_engine(model, params, enabled=False)
    cfg = ServingConfig(max_queue_depth=8,
                        prefix_cache=PrefixCacheConfig(enabled=True,
                                                       max_cached_blocks=16))
    fe = ServingFrontend([engine], cfg)
    try:
        assert engine.state_manager.prefix_cache_enabled
        assert engine.state_manager.prefix_cache_max_blocks == 16
        rng = np.random.default_rng(4)
        sys_p = rng.integers(0, VOCAB, size=24).tolist()
        h1 = fe.submit(sys_p + [1], max_new_tokens=2)
        assert h1._req.wait(60)
        h2 = fe.submit(sys_p + [2], max_new_tokens=2)
        assert h2._req.wait(60)
        assert fe.metrics_snapshot()["prefix_tokens_saved"] >= 16
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_deadline_expiry_under_prefix_sharing(model_and_params):
    """Frontend deadline expiry with a shared prefix: the expired request
    releases only its own references; refcounts never go negative and the
    pool returns to total once all requests are done."""
    from deepspeed_tpu.serving import RequestState, ServingConfig, \
        ServingFrontend

    model, params = model_and_params
    engine = make_engine(model, params, enabled=True)
    fe = ServingFrontend([engine], ServingConfig(max_queue_depth=8))
    try:
        rng = np.random.default_rng(2)
        sys_p = rng.integers(0, VOCAB, size=24).tolist()
        warm = fe.submit(sys_p + [1, 2], max_new_tokens=2)
        assert warm._req.wait(60)
        doomed = fe.submit(sys_p + [3, 4], max_new_tokens=90,
                           deadline_ms=100.0)
        ok = fe.submit(sys_p + [5, 6], max_new_tokens=4)
        assert doomed._req.wait(60) and ok._req.wait(60)
        assert doomed.state == RequestState.EXPIRED
        assert ok.state == RequestState.FINISHED
        mgr = engine.state_manager
        deadline = time.monotonic() + 10
        while mgr.available_blocks != engine.config.kv_blocks \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert mgr.available_blocks == engine.config.kv_blocks
        for b in mgr._index.values():
            assert mgr.allocator.ref_count(b) == 1
        snap = fe.metrics_snapshot()
        assert snap["requests_expired"] == 1
        assert snap["prefix_tokens_saved"] >= 16  # sharers matched the prefix
    finally:
        fe.shutdown(drain=False, timeout=5)
