"""int8/fp8 weight serving (ISSUE 13 tentpole): the CausalLM param tree
quantized once at engine build (inference/v2/weight_quant.py), every
matmul running from the quantized tree via ops/quantizer.quantized_matmul,
config plumbing across engine/serving/runtime, per-replica apply on every
frontend build path (boot, restart, autoscaler grow), param-byte
observability, and TP scale-plane sharding. Disabled must be
byte-for-byte the historical pytree and program."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2 import weight_quant as WQ
from deepspeed_tpu.inference.v2.testing import (assert_greedy_parity,
                                                greedy_generate)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

VOCAB = 128
BS = 16


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=4,
                            max_seq_len=256, norm="rmsnorm",
                            activation="silu", position="rope")
    model = CausalLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def untied_model_and_params():
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=4,
                            max_seq_len=256, norm="rmsnorm",
                            activation="silu", position="rope",
                            tie_embeddings=False)
    model = CausalLM(cfg)
    return model, model.init(jax.random.PRNGKey(1))


def make_engine(model, params, wq=True, **cfg_kw):
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=256, max_ragged_sequence_count=8,
        max_chunk_tokens=32, kv_blocks=64, kv_block_size=BS,
        max_tracked_sequences=64, weight_quant_enabled=wq, **cfg_kw)
    return InferenceEngineV2(model, params=params, config=vcfg)


def rand_prompt(rng, n):
    return rng.integers(0, VOCAB, size=n).tolist()


# -------------------------------------------------------- tree + byte math
def test_quantized_tree_structure_and_bytes(model_and_params):
    model, params = model_and_params
    qparams, stats = WQ.quantize_weights(model.cfg, params)
    layers = qparams["layers"]
    for name in ("wq", "wk", "wv", "wo", "w_in", "w_out", "w_gate"):
        assert WQ.is_quantized(layers[name]), name
        node = layers[name]
        assert node["qw"].dtype == jnp.int8
        assert node["qs"].dtype == jnp.float32
        assert node["qw"].shape == params["layers"][name].shape
        # scales: same leading dims, last dim = groups
        assert node["qs"].shape[:-1] == node["qw"].shape[:-1]
    # non-matmul leaves untouched (same objects, not copies)
    assert qparams["embed"]["wte"] is params["embed"]["wte"]
    assert qparams["final_norm"]["w"] is params["final_norm"]["w"]
    assert layers["attn_norm_w"] is params["layers"]["attn_norm_w"]
    # byte accounting: the quantized share cut >= 3.5x vs its fp32 form
    fp32_matmul_bytes = sum(
        WQ._leaf_bytes(params["layers"][n])
        for n in ("wq", "wk", "wv", "wo", "w_in", "w_out", "w_gate"))
    assert fp32_matmul_bytes / stats["param_bytes_quantized"] >= 3.5
    assert stats["param_bytes_total"] < WQ.param_stats(params)[
        "param_bytes_total"]
    assert stats["params_quantized"] == 7


def test_skip_list_and_untied_lm_head(untied_model_and_params):
    model, params = untied_model_and_params
    qparams, stats = WQ.quantize_weights(model.cfg, params)
    assert WQ.is_quantized(qparams["lm_head"]["w"])
    skipped, stats2 = WQ.quantize_weights(model.cfg, params,
                                          skip=["lm_head", "wq"])
    assert skipped["lm_head"]["w"] is params["lm_head"]["w"]
    assert skipped["layers"]["wq"] is params["layers"]["wq"]
    assert stats2["params_quantized"] == stats["params_quantized"] - 2


def test_validate_rejects_unknown():
    WQ.validate_weight_quant("int8", 128)
    WQ.validate_weight_quant("fp8_e4m3", 64)
    with pytest.raises(ValueError, match="dtype"):
        WQ.validate_weight_quant("int4", 128)
    with pytest.raises(ValueError, match="block"):
        WQ.validate_weight_quant("int8", 0)


# ----------------------------------------------------- disabled byte-parity
def test_disabled_path_byte_identical(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(0)
    prompt = rand_prompt(rng, 30)
    eng_default = InferenceEngineV2(
        model, params=params,
        config=RaggedInferenceEngineConfig(
            max_ragged_batch_size=256, max_ragged_sequence_count=8,
            max_chunk_tokens=32, kv_blocks=64, kv_block_size=BS))
    eng_off = make_engine(model, params, wq=False)
    la = np.asarray(eng_default.put([1], [prompt]))
    lb = np.asarray(eng_off.put([1], [prompt]))
    np.testing.assert_array_equal(la, lb)
    # pytree untouched: identical leaves, no {"qw","qs"} nodes anywhere
    assert eng_off.params is params
    assert not any(WQ.is_quantized(l) for l in
                   jax.tree.leaves(eng_off.params, is_leaf=WQ.is_quantized)
                   if isinstance(l, dict))


def test_disabled_greedy_stream_identical(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(1)
    prompts = [rand_prompt(rng, 25), rand_prompt(rng, 18)]
    g_default = greedy_generate(
        InferenceEngineV2(model, params=params,
                          config=RaggedInferenceEngineConfig(
                              max_ragged_batch_size=256,
                              max_ragged_sequence_count=8,
                              max_chunk_tokens=32, kv_blocks=64,
                              kv_block_size=BS)),
        prompts, uid_base=1, max_new_tokens=10)
    g_off = greedy_generate(make_engine(model, params, wq=False),
                            prompts, uid_base=1, max_new_tokens=10)
    assert_greedy_parity(g_default, g_off, label="weight_quant disabled")


# ------------------------------------------------- quality gates (quant on)
@pytest.mark.parametrize("wdtype", ["int8", "fp8_e4m3"])
def test_bounded_divergence_and_logit_error(model_and_params, wdtype):
    model, params = model_and_params
    rng = np.random.default_rng(2)
    prompts = [rand_prompt(rng, 30) for _ in range(3)]
    g_off = greedy_generate(make_engine(model, params, wq=False),
                            prompts, uid_base=1, max_new_tokens=16)
    g_on = greedy_generate(
        make_engine(model, params, wq=True, weight_quant_dtype=wdtype),
        prompts, uid_base=1, max_new_tokens=16)
    fracs = []
    for a, b in zip(g_off, g_on):
        matched = next((i for i, (x, y) in enumerate(zip(a, b)) if x != y),
                       min(len(a), len(b)))
        fracs.append(matched / max(1, len(a)))
    assert np.mean(fracs) >= 0.5, f"divergence too large: {fracs}"
    p = prompts[0]
    la = np.asarray(make_engine(model, params, wq=False).put([9], [p]))
    lb = np.asarray(make_engine(model, params, wq=True,
                                weight_quant_dtype=wdtype).put([9], [p]))
    rel = np.max(np.abs(la - lb)) / (np.max(np.abs(la)) + 1e-9)
    assert rel < 0.05, f"relative logit error {rel}"


def test_perplexity_delta_gate(model_and_params):
    """Teacher-forced perplexity of the int8-weight engine within 1% of
    the full-precision engine (the bench weight_quant phase's gate, in
    miniature) — and the verify_width path rides the quantized tree."""
    model, params = model_and_params
    rng = np.random.default_rng(3)
    toks = rand_prompt(rng, 64)
    chunk = 16

    def nll(eng, uid):
        total, count = 0.0, 0
        for lo in range(0, len(toks), chunk):
            ch = toks[lo:lo + chunk]
            logits = np.asarray(eng.put([uid], [ch],
                                        verify_width=len(ch)))[0]
            for j in range(len(ch)):
                t = lo + j + 1
                if t >= len(toks):
                    break
                row = logits[j].astype(np.float64)
                lse = row.max() + np.log(np.exp(row - row.max()).sum())
                total += lse - row[toks[t]]
                count += 1
        return total / count

    ppl_off = np.exp(nll(make_engine(model, params, wq=False), 1))
    ppl_on = np.exp(nll(make_engine(model, params, wq=True), 1))
    assert abs(ppl_on / ppl_off - 1.0) <= 0.01, (ppl_off, ppl_on)


def test_composes_with_quantized_kv(model_and_params):
    """Weight quant + KV quant (int8 and fp8) on one engine: both
    representations active, decode proceeds, logits stay close to the
    full-precision engine."""
    model, params = model_and_params
    rng = np.random.default_rng(4)
    prompt = rand_prompt(rng, 30)
    la = np.asarray(make_engine(model, params, wq=False).put([1], [prompt]))
    for kdtype in ("int8", "fp8_e4m3"):
        eng = make_engine(model, params, wq=True, kv_quant_enabled=True,
                          kv_quant_dtype=kdtype)
        lb = np.asarray(eng.put([1], [prompt]))
        rel = np.max(np.abs(la - lb)) / (np.max(np.abs(la)) + 1e-9)
        assert rel < 0.05, (kdtype, rel)
        for _ in range(3):
            lb = np.asarray(eng.put([1], [[int(np.argmax(lb))]]))


# ------------------------------------------------------- configure + guards
def test_configure_weight_quant_guards(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(5)
    eng = make_engine(model, params, wq=False)
    eng.put([1], [rand_prompt(rng, 10)])
    with pytest.raises(RuntimeError, match="tracked"):
        eng.configure_weight_quant(True)
    eng.flush(1)
    eng.configure_weight_quant(True)
    assert eng.config.weight_quant_enabled
    assert WQ.is_quantized(eng.params["layers"]["wq"])
    # idempotent re-apply with the same representation
    eng.configure_weight_quant(True)
    # lossy: disable or re-code raises
    with pytest.raises(RuntimeError, match="already quantized"):
        eng.configure_weight_quant(False)
    with pytest.raises(RuntimeError, match="already quantized"):
        eng.configure_weight_quant(True, dtype="fp8_e4m3")
    # bad dtype rejected before touching anything
    eng2 = make_engine(model, params, wq=False)
    with pytest.raises(ValueError, match="dtype"):
        eng2.configure_weight_quant(True, dtype="int3")
    assert not eng2.config.weight_quant_enabled


def test_param_stats_shape(model_and_params):
    model, params = model_and_params
    off = make_engine(model, params, wq=False)
    on = make_engine(model, params, wq=True)
    s_off, s_on = off.param_stats(), on.param_stats()
    assert s_off["param_bytes_quantized"] == 0
    assert s_on["param_bytes_quantized"] > 0
    assert s_on["param_bytes_total"] < s_off["param_bytes_total"]
    assert s_on["weight_quant_dtype"] == "int8"


# -------------------------------------------------- serving config + gauges
def test_serving_config_applies_weight_quant(model_and_params):
    from deepspeed_tpu.serving import (ServingConfig, ServingFrontend,
                                       WeightQuantConfig)

    model, params = model_and_params
    wq = WeightQuantConfig(enabled=True)
    vcfg = RaggedInferenceEngineConfig()
    wq.apply(vcfg)
    assert vcfg.weight_quant_enabled and vcfg.weight_quant_dtype == "int8"
    assert vcfg.weight_quant_skip == ["embed", "final_norm"]
    eng = make_engine(model, params, wq=False)
    fe = ServingFrontend([eng],
                         ServingConfig(weight_quant={"enabled": True}))
    try:
        assert eng.config.weight_quant_enabled
        assert WQ.is_quantized(eng.params["layers"]["wq"])
        rng = np.random.default_rng(6)
        h = fe.submit(rand_prompt(rng, 20), max_new_tokens=4)
        assert fe.wait_all([h], timeout=60)
        snap = fe.metrics_snapshot()
        stats = eng.param_stats()
        assert snap["param_bytes_total"] == stats["param_bytes_total"]
        assert snap["param_bytes_quantized"] == \
            stats["param_bytes_quantized"]
        assert snap["param_bytes_quantized"] > 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_ds_config_mounts_weight_quant():
    from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig

    c = DeepSpeedTpuConfig(**{
        "train_micro_batch_size_per_gpu": 1,
        "weight_quant": {"enabled": True, "dtype": "fp8_e4m3"},
        "serving": {"weight_quant": {"enabled": True, "block": 64}}})
    assert c.weight_quant.enabled and c.weight_quant.dtype == "fp8_e4m3"
    assert c.serving.weight_quant.block == 64


# ---------------------------------------------------------------- TP serving
def test_tp_sharded_weight_quant_matches_single_device(model_and_params):
    """TP serving from a quantized tree: the scale planes shard with
    their weight shards (expand_spec_tree mirrors the logical spec onto
    qw and qs), so — at a block that divides the per-shard width, making
    the representation TP-invariant — the sharded engine matches the
    single-device quantized engine exactly."""
    from deepspeed_tpu.parallel import topology as topo

    model, params = model_and_params

    def vcfg():
        return RaggedInferenceEngineConfig(
            max_ragged_batch_size=256, max_ragged_sequence_count=8,
            max_chunk_tokens=32, kv_blocks=64, kv_block_size=BS,
            max_tracked_sequences=64, weight_quant_enabled=True,
            weight_quant_block=16)

    single = InferenceEngineV2(model, params=params, config=vcfg())
    topo.reset_topology()
    t = topo.MeshTopology.build(data=4, tensor=2)
    sharded = InferenceEngineV2(model, params=params, mesh=t, config=vcfg())
    node = sharded.params["layers"]["wq"]
    assert WQ.is_quantized(node)
    assert "tensor" in str(node["qw"].sharding.spec)
    assert "tensor" in str(node["qs"].sharding.spec)
    rng = np.random.default_rng(7)
    prompts = {1: rand_prompt(rng, 7), 2: rand_prompt(rng, 12)}
    for uid, p in prompts.items():
        a = np.asarray(single.put([uid], [p]))
        b = np.asarray(sharded.put([uid], [p]))
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)
    for step in range(3):
        nxt = [[int(rng.integers(0, VOCAB))] for _ in prompts]
        a = np.asarray(single.put(list(prompts), nxt))
        b = np.asarray(sharded.put(list(prompts), nxt))
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5,
                                   err_msg=f"decode step {step}")
    topo.reset_topology()


# ----------------------------------------------- autoscaler grow composition
def test_fleet_scale_up_applies_weight_quant_before_traffic(
        model_and_params):
    """Regression for the PR 12 grow path silently skipping per-replica
    config apply: a FleetController scale-up must build the new replica
    through the frontend's full wiring, so weight_quant is applied to
    the factory-fresh engine BEFORE it takes traffic (structurally
    guaranteed: configure_weight_quant raises once sequences are
    tracked, so a grown replica that serves at all was quantized
    first)."""
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend

    model, params = model_and_params
    built = []

    def factory(i):
        eng = make_engine(model, params, wq=False)
        built.append(eng)
        return eng

    scfg = ServingConfig(
        max_queue_depth=256,
        weight_quant={"enabled": True},
        autoscaler={"enabled": True, "min_replicas": 1, "max_replicas": 3,
                    "scale_up_queue_per_replica": 2.0,
                    "up_stable_ticks": 1, "scale_up_cooldown_s": 0.1,
                    "down_stable_ticks": 1000,
                    "tick_interval_s": 0.05})
    rng = np.random.default_rng(8)
    fe = ServingFrontend([factory(0)], scfg, engine_factory=factory)
    try:
        hs = [fe.submit(rand_prompt(rng, int(rng.integers(8, 20))),
                        max_new_tokens=24) for _ in range(24)]
        assert fe.wait_all(hs, timeout=600)
        deadline = time.monotonic() + 60
        while (time.monotonic() < deadline
               and fe.autoscaler.stats()["scale_ups"] < 1):
            time.sleep(0.05)
        assert fe.autoscaler.stats()["scale_ups"] >= 1, \
            "burst never grew the fleet"
        assert len(built) >= 2, "factory never built a grown replica"
        for eng in built:
            assert eng.config.weight_quant_enabled
            assert WQ.is_quantized(eng.params["layers"]["wq"])
        snap = fe.metrics_snapshot()
        assert snap["requests_completed"] == 24
        # fleet-summed param gauges cover every accepting replica
        assert snap["param_bytes_quantized"] > 0
    finally:
        fe.shutdown(drain=False, timeout=5)
