"""ZeRO++ (qwZ/qgZ/hpZ) + quantizer kernels.

Mirrors reference tests/unit/runtime/zero/test_zeropp.py (train with
quantized collectives, check convergence) plus kernel-level numerics for
the quantization ops (reference tests/unit/ops quantizer tests)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import build_model
from deepspeed_tpu.ops import quantizer as qz
from deepspeed_tpu.parallel import topology as topo


# ------------------------------------------------------------- quant kernels

def test_int8_roundtrip_error():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 256)), jnp.float32)
    q, s = qz.quantize_blockwise(x, bits=8, block=128)
    assert q.dtype == jnp.int8 and s.shape == (64, 2)
    y = qz.dequantize_blockwise(q, s, block=128)
    # int8 symmetric block quant: error bounded by scale/2 per element
    bound = np.asarray(s).repeat(128, axis=-1) * 0.5 + 1e-7
    assert (np.abs(np.asarray(x - y)) <= bound).all()


def test_int4_roundtrip_and_packing():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
    q, s = qz.quantize_blockwise(x, bits=4, block=64)
    assert int(jnp.max(jnp.abs(q))) <= 7
    packed = qz.pack_int4(q)
    assert packed.shape == (8, 32) and packed.dtype == jnp.uint8
    np.testing.assert_array_equal(np.asarray(qz.unpack_int4(packed)),
                                  np.asarray(q))
    y = qz.dequantize_blockwise(q, s, block=64)
    bound = np.asarray(s).repeat(64, axis=-1) * 0.5 + 1e-7
    assert (np.abs(np.asarray(x - y)) <= bound).all()


def test_pallas_quant_matches_xla(monkeypatch):
    monkeypatch.setattr(qz, "_FORCE_INTERPRET", True)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((16, 256)), jnp.float32)
    qp, sp = qz._quantize_pallas(x, 8, 128)
    qx, sx = qz._quantize_xla(x, 8, 128)
    np.testing.assert_array_equal(np.asarray(qp), np.asarray(qx))
    np.testing.assert_allclose(np.asarray(sp), np.asarray(sx), rtol=1e-6)
    yp = qz._dequantize_pallas(qp, sp, 128, jnp.float32)
    yx = qz._dequantize_xla(qx, sx, 128, jnp.float32)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(yx), rtol=1e-6)


def test_choose_block():
    assert qz.choose_block(256) == 128
    assert qz.choose_block(96) == 96
    assert qz.choose_block(100, 64) == 50


# --------------------------------------------------------------- train-level

def _make_engine(extra_zero=None, mesh=None, lr=1e-2):
    topo.reset_topology()
    zero = {"stage": 3}
    zero.update(extra_zero or {})
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": lr}},
        "zero_optimization": zero,
        "mesh": mesh or {"data": -1, "fsdp": 2, "tensor": 2},
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                               config=config)
    return engine


def _train(engine, steps=6, seed=0):
    rng = np.random.default_rng(seed)
    dp = engine.topology.get_data_parallel_world_size()
    batch = {"input_ids": rng.integers(0, 256, size=(2 * dp, 33),
                                       dtype=np.int64)}
    it = itertools.repeat(batch)
    return [float(engine.train_batch(it)) for _ in range(steps)]


def _micro_hlo(engine):
    """Compiled HLO text of the micro (fwd+bwd) program."""
    rng = np.random.default_rng(0)
    dp = engine.topology.get_data_parallel_world_size()
    batch = {"input_ids": jnp.asarray(rng.integers(0, 256, size=(2 * dp, 33)))}
    lowered = engine._micro_fn.lower(engine.state, batch, jax.random.PRNGKey(0))
    return lowered.compile().as_text()


def test_qwz_quantizes_weight_allgather():
    engine = _make_engine({"zero_quantized_weights": True})
    assert engine.module.layer_transform is not None
    hlo = _micro_hlo(engine)
    # the weight all-gather must move int8, not f32
    assert any("all-gather" in line and "s8[" in line
               for line in hlo.splitlines()), "no int8 all-gather in HLO"
    losses = _train(engine)
    assert losses[-1] < losses[0] - 0.5, f"no convergence: {losses}"


@pytest.mark.slow
def test_qgz_quantizes_grad_reduce():
    engine = _make_engine({"zero_quantized_weights": True,
                           "zero_quantized_gradients": True})
    hlo = _micro_hlo(engine)
    assert any("all-to-all" in line and "s8[" in line
               for line in hlo.splitlines()), "no int8 all-to-all in HLO"
    losses = _train(engine)
    assert losses[-1] < losses[0] - 0.5, f"no convergence: {losses}"


@pytest.mark.slow
def test_qwz_loss_close_to_fp():
    fp = _train(_make_engine())
    qw = _train(_make_engine({"zero_quantized_weights": True}))
    # same trajectory within quantization tolerance
    assert abs(fp[0] - qw[0]) < 0.15
    assert abs(fp[-1] - qw[-1]) < 0.6


def test_zeropp_requires_stage3():
    with pytest.raises(ValueError, match="stage"):
        _make_engine({"stage": 2, "zero_quantized_weights": True})


def test_hpz_opt_state_sharding():
    engine = _make_engine({"zero_hpz_partition_size": 2})
    mom = jax.tree_util.tree_flatten(
        engine._opt_shardings.moments, is_leaf=lambda x: hasattr(x, "spec"))[0]
    found = False
    for ns in mom:
        for entry in ns.spec:
            if isinstance(entry, tuple) and set(entry) == {"fsdp", "data"}:
                found = True
    assert found, "hpZ: no moment sharded over (fsdp, data)"
    # params stay fsdp-only (weight gathers ride the small group)
    for ns in jax.tree_util.tree_flatten(
            engine._param_shardings, is_leaf=lambda x: hasattr(x, "spec"))[0]:
        for entry in ns.spec:
            assert not (isinstance(entry, tuple) and "data" in entry)
    losses = _train(engine)
    assert losses[-1] < losses[0] - 0.5
