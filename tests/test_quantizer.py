"""ops/quantizer.py hardening (ISSUE 13 satellite): ragged-tail
round-trips, Pallas-vs-XLA quant/dequant parity, int4 pack/unpack
coverage, fp8_e4m3 groups, and the quantized_matmul serving hot op —
all previously untested in tier-1."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import quantizer as Q

RNG = np.random.default_rng(0)


def randf(*shape):
    return jnp.asarray(RNG.normal(size=shape), jnp.float32)


# ---------------------------------------------------------- ragged tails
class TestRaggedTail:
    @pytest.mark.parametrize("n,block", [(100, 32), (7, 4), (130, 128),
                                         (33, 32)])
    def test_int8_round_trip(self, n, block):
        x = randf(3, n)
        q, s = Q.quantize_blockwise(x, block=block)
        assert q.shape == x.shape and q.dtype == jnp.int8
        assert s.shape == (3, -(-n // block))
        xr = Q.dequantize_blockwise(q, s, block=block)
        # worst-case step is amax/127 per group
        bound = float(jnp.max(jnp.abs(x))) / 127 + 1e-6
        assert float(jnp.max(jnp.abs(xr - x))) <= bound

    def test_tail_group_scales_against_own_amax(self):
        # big values in the body, tiny tail: a shared scale would crush
        # the tail to zero — its own group must preserve it
        x = jnp.concatenate([100.0 * randf(1, 64),
                             0.01 * randf(1, 5)], axis=-1)
        q, s = Q.quantize_blockwise(x, block=64)
        xr = np.asarray(Q.dequantize_blockwise(q, s, block=64))
        tail = np.asarray(x)[0, 64:]
        np.testing.assert_allclose(xr[0, 64:], tail,
                                   atol=np.abs(tail).max() / 100)

    def test_block_inference_refuses_ragged(self):
        x = randf(2, 33)
        q, s = Q.quantize_blockwise(x, block=32)  # groups = 2, 33 % 2 != 0
        with pytest.raises(ValueError, match="ragged"):
            Q.dequantize_blockwise(q, s)          # block not inferable
        # divisible case still infers
        q2, s2 = Q.quantize_blockwise(randf(2, 96), block=32)
        assert Q.dequantize_blockwise(q2, s2).shape == (2, 96)

    def test_ragged_layout_needs_its_block_back(self):
        """The undetectable ragged subcase (group count divides N):
        inference would silently assume the divisor layout, so ragged
        layouts must round-trip their explicit block — passing it back
        is exact, and the divisor-layout inference on the SAME shapes
        is a different (wrong for this data) segmentation."""
        x = jnp.asarray([[8.0, 8.0, 8.0, 8.0, 0.5, 0.5]], jnp.float32)
        q, s = Q.quantize_blockwise(x, block=4)   # groups = 2, 6 % 2 == 0
        exact = np.asarray(Q.dequantize_blockwise(q, s, block=4))
        np.testing.assert_allclose(exact, np.asarray(x), atol=8 / 127 + 1e-6)
        # inference assumes block = 3: element 3 (an 8.0 in the true
        # group 0) lands in the inferred tail group and dequantizes with
        # the 0.5-amax scale — materially wrong, which is why the
        # contract requires the explicit block
        inferred = np.asarray(Q.dequantize_blockwise(q, s))
        assert abs(inferred[0, 3] - 8.0) > 1.0

    def test_int4_ragged_round_trip(self):
        x = randf(2, 50)
        q, s = Q.quantize_blockwise(x, bits=4, block=16)
        assert int(jnp.max(jnp.abs(q))) <= 7
        xr = Q.dequantize_blockwise(q, s, block=16)
        bound = float(jnp.max(jnp.abs(x))) / 7 + 1e-6
        assert float(jnp.max(jnp.abs(xr - x))) <= bound


# ------------------------------------------------- Pallas-vs-XLA parity
class TestPallasParity:
    def test_quant_dequant_parity(self, monkeypatch):
        """The Pallas (quantize, dequantize) pair in interpret mode must
        match the XLA formulation bit for bit — same rounding, same
        scale math."""
        x = randf(16, 256)
        qx, sx = Q._quantize_xla(x, 8, 128)
        monkeypatch.setattr(Q, "_FORCE_INTERPRET", True)
        qp, sp = Q.quantize_blockwise(x, block=128)
        np.testing.assert_array_equal(np.asarray(qp), np.asarray(qx))
        # scales agree to the ulp (the kernel's amax/qmax association
        # may differ from XLA's by one rounding)
        np.testing.assert_allclose(np.asarray(sp), np.asarray(sx),
                                   rtol=1e-6)
        op = Q.dequantize_blockwise(qp, sp, block=128)
        monkeypatch.setattr(Q, "_FORCE_INTERPRET", False)
        ox = Q.dequantize_blockwise(qx, sx, block=128)
        np.testing.assert_allclose(np.asarray(op), np.asarray(ox),
                                   atol=1e-7)

    def test_quantized_matmul_parity(self, monkeypatch):
        w = randf(64, 256)
        qw, qs = Q.quantize_blockwise(w, block=128)
        x = randf(8, 64)
        ref = Q.quantized_matmul(x, qw, qs)                     # XLA
        monkeypatch.setattr(Q, "_FORCE_INTERPRET", True)
        out = Q.quantized_matmul(x, qw, qs)                     # Pallas
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        # and both equal the explicit dequant-then-dot reference
        dense = (x.astype(jnp.float32)
                 @ Q.dequantize_blockwise(qw, qs, block=128))
        np.testing.assert_allclose(np.asarray(ref), np.asarray(dense),
                                   atol=1e-5, rtol=1e-5)


# ------------------------------------------------------------ int4 pack
class TestInt4Pack:
    def test_round_trip(self):
        q = jnp.asarray(RNG.integers(-7, 8, size=(3, 32)), jnp.int8)
        p = Q.pack_int4(q)
        assert p.dtype == jnp.uint8 and p.shape == (3, 16)
        np.testing.assert_array_equal(np.asarray(Q.unpack_int4(p)),
                                      np.asarray(q))

    def test_sign_extension_extremes(self):
        q = jnp.asarray([[-7, 7, 0, -1]], jnp.int8)
        np.testing.assert_array_equal(
            np.asarray(Q.unpack_int4(Q.pack_int4(q))), np.asarray(q))

    def test_quantize_pack_dequantize_chain(self):
        x = randf(4, 64)
        q, s = Q.quantize_blockwise(x, bits=4, block=32)
        q2 = Q.unpack_int4(Q.pack_int4(q))
        xr = Q.dequantize_blockwise(q2, s, block=32)
        bound = float(jnp.max(jnp.abs(x))) / 7 + 1e-6
        assert float(jnp.max(jnp.abs(xr - x))) <= bound


# ------------------------------------------------------------------- fp8
class TestFP8:
    def test_round_trip_relative_error(self):
        x = randf(4, 128)
        q, s = Q.quantize_blockwise(x, block=64, dtype="fp8_e4m3")
        assert q.dtype == jnp.float8_e4m3fn
        xr = Q.dequantize_blockwise(q, s, block=64)
        # e4m3: ~2^-3 relative step near amax
        rel = float(jnp.max(jnp.abs(xr - x)) / jnp.max(jnp.abs(x)))
        assert rel <= 0.07, rel

    def test_fp8_matmul_matches_dequant_reference(self):
        w = randf(32, 128)
        qw, qs = Q.quantize_blockwise(w, block=128, dtype="fp8_e4m3")
        x = randf(4, 32)
        out = Q.quantized_matmul(x, qw, qs)
        dense = (x.astype(jnp.float32)
                 @ Q.dequantize_blockwise(qw, qs, block=128))
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=1e-5, rtol=1e-5)

    def test_zero_group_is_exact(self):
        x = jnp.zeros((2, 64), jnp.float32)
        q, s = Q.quantize_blockwise(x, block=32, dtype="fp8_e4m3")
        assert float(jnp.max(jnp.abs(
            Q.dequantize_blockwise(q, s, block=32)))) == 0.0
