"""ZeRO-Inference weight-only quantization (int8/int4 QuantTensor params).

Mirrors reference tests/unit/inference/quantization/test_intX_quantization.py:
quantized model output stays close to fp, memory shrinks accordingly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.quantization import (QuantTensor,
                                                  quantize_param_tree,
                                                  tree_nbytes)
from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.transformer import TINY_TEST, CausalLM
from deepspeed_tpu.parallel import topology as topo


@pytest.fixture(scope="module")
def fp_model():
    import dataclasses

    cfg = dataclasses.replace(TINY_TEST, hidden_size=128, num_heads=4,
                              num_kv_heads=4, intermediate_size=256,
                              vocab_size=512)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def test_quant_tree_structure_and_bytes(fp_model):
    model, params = fp_model
    q8 = quantize_param_tree(params, bits=8)
    assert isinstance(q8["layers"]["wq"], QuantTensor)
    # 1-D norm weights stay fp
    assert not isinstance(q8["layers"]["attn_norm_w"], QuantTensor)
    fp_bytes = tree_nbytes(params)
    assert tree_nbytes(q8) < 0.4 * fp_bytes
    q4 = quantize_param_tree(params, bits=4)
    assert q4["layers"]["wq"].packed
    assert tree_nbytes(q4) < 0.25 * fp_bytes


@pytest.mark.parametrize("bits,tol", [(8, 0.08), (4, 0.6)])
def test_quantized_forward_close(fp_model, bits, tol):
    model, params = fp_model
    qp = quantize_param_tree(params, bits=bits)
    toks = jnp.asarray(np.random.default_rng(0).integers(
        0, model.cfg.vocab_size, size=(2, 16)), jnp.int32)
    fp = np.asarray(model.apply(params, toks), np.float32)
    q = np.asarray(model.apply(qp, toks), np.float32)
    # relative error on logits
    rel = np.abs(q - fp).mean() / (np.abs(fp).mean() + 1e-9)
    assert rel < tol, f"relative logit error {rel} at {bits} bits"
    if bits == 8:
        # argmax (greedy decision) preserved for most positions
        agree = (fp.argmax(-1) == q.argmax(-1)).mean()
        assert agree > 0.9


def test_engine_quant_config(fp_model):
    model, params = fp_model
    topo.reset_topology()
    engine = deepspeed_tpu.init_inference(
        model, params=params, dtype="fp32", quant={"enabled": True, "bits": 8})
    assert isinstance(engine.params["layers"]["wq"], QuantTensor)
    toks = np.random.default_rng(1).integers(0, model.cfg.vocab_size,
                                             size=(1, 8))
    out = engine.generate(toks, max_new_tokens=4)
    assert out.shape == (1, 12)
    fp_logits = np.asarray(model.apply(params, jnp.asarray(toks, jnp.int32)))
    q_logits = np.asarray(engine.forward(toks))
    rel = np.abs(q_logits - fp_logits).mean() / (np.abs(fp_logits).mean() + 1e-9)
    assert rel < 0.08
    topo.reset_topology()


def test_quant_tensor_scan_slicing(fp_model):
    """QuantTensor leaves survive lax.scan slicing over the layer dim."""
    model, params = fp_model
    qp = quantize_param_tree(params, bits=8)
    stacked = qp["layers"]["wq"]

    def body(carry, layer_qt):
        return carry + jnp.sum(layer_qt.astype(jnp.float32)), None

    total, _ = jax.lax.scan(body, jnp.zeros(()), stacked)
    expect = jnp.sum(stacked.astype(jnp.float32))
    np.testing.assert_allclose(float(total), float(expect), rtol=1e-5)
