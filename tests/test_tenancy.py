"""Multi-model, multi-tenant serving (ISSUE 16, docs/SERVING.md
"Multi-model & multi-tenant serving").

Covers the tenancy subsystem at every layer: ``TenantPolicy`` /
``ModelSpec`` config validation, the :class:`TenantLedger` unit
behaviors under an injectable clock (weight-normalized virtual service
with re-flooring, sliding-window token-rate quota with edge-fired
journal events and the over-quota gauge, idempotent per-engine KV block
charges released on reconcile), the admission queue's deficit-weighted-
fair pop and over-quota-first victim ordering, the per-tenant SLO rule
derivation, and the frontend end to end: unknown model/tenant refused
BEFORE counters (the PR-8 rejection-ordering contract), legacy
``submit()`` call sites untouched, a tenant-A flood unable to starve
tenant B under fair ordering (and provably starving it with tenancy
off), per-tenant metric series + health-report books, and the
multi-model registry routing every request to a replica of its own
pool. Tenancy/models off must stay byte-identical to the historical
stack — asserted on the metrics snapshot and pop order.
"""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.serving import ServingConfig, ServingFrontend
from deepspeed_tpu.serving.config import ModelSpec, TenantPolicy
from deepspeed_tpu.serving.metrics import serving_metrics
from deepspeed_tpu.serving.queue import AdmissionQueue
from deepspeed_tpu.serving.request import (FinishReason, RequestState,
                                           ServingRequest)
from deepspeed_tpu.serving.tenancy import TenantLedger, kv_blocks_for
from deepspeed_tpu.telemetry.slo import AlertEngine, SLOClassTarget, SLOConfig

VOCAB = 128
MODEL_KW = dict(vocab_size=VOCAB, hidden_size=32, intermediate_size=64,
                num_layers=1, num_heads=2, max_seq_len=128, norm="rmsnorm",
                activation="silu", position="rope")
ENGINE_KW = dict(max_ragged_batch_size=64, max_ragged_sequence_count=4,
                 max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
                 max_tracked_sequences=32)

_model = None
_params = None


def tiny_engine(**cfg_over):
    global _model, _params
    import jax

    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    if _model is None:
        _model = CausalLM(TransformerConfig(**MODEL_KW))
        _params = _model.init(jax.random.PRNGKey(0))
    base = dict(ENGINE_KW)
    base.update(cfg_over)
    return InferenceEngineV2(_model, params=_params,
                             config=RaggedInferenceEngineConfig(**base))


def _req(plen=8, max_new=4, priority=1, deadline_s=None, tenant="default",
         request_class="interactive", shed_rank=0, model_id="default"):
    return ServingRequest([1] * plen, max_new, priority, deadline_s, None,
                          request_class=request_class, shed_rank=shed_rank,
                          tenant=tenant, model_id=model_id)


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class _FakeJournal:
    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append((kind, fields))


class _FakeReplica:
    """Just enough surface for the ledger's KV budget math."""

    class _Cfg:
        kv_block_size = 8

    class _Eng:
        config = None

    def __init__(self, replica_id=0):
        self.replica_id = replica_id
        self.engine = _FakeReplica._Eng()
        self.engine.config = _FakeReplica._Cfg()


def _ledger(policies, clock=None, journal=None, metrics=None, window_s=10.0):
    pols = {name: TenantPolicy(**kw) for name, kw in policies.items()}
    pols.setdefault("default", TenantPolicy())
    return TenantLedger(pols, metrics=metrics, journal=journal,
                        window_s=window_s,
                        clock=clock or _Clock())


# ============================================================== config
class TestTenancyConfig:
    def test_tenant_policy_validation(self):
        with pytest.raises(ValueError):
            TenantPolicy(weight=0.0)
        with pytest.raises(ValueError):
            TenantPolicy(weight=-1.0)
        with pytest.raises(ValueError):
            TenantPolicy(token_rate=-1.0)
        with pytest.raises(ValueError):
            TenantPolicy(kv_block_budget=-1)

    def test_default_tenant_merged_only_when_enabled(self):
        on = ServingConfig(tenants={"alpha": {"weight": 2.0}})
        assert set(on.tenants) == {"alpha", "default"}
        off = ServingConfig()
        assert off.tenants == {}, \
            "empty tenants map must stay empty (tenancy off)"

    def test_model_spec_validation(self):
        with pytest.raises(ValueError):
            ModelSpec(replicas=0)          # no members at all
        ModelSpec(replicas=0, peers=["10.0.0.1:7000"])   # peers suffice
        with pytest.raises(ValueError):
            ModelSpec(peers=["not-an-address"])
        with pytest.raises(ValueError):
            ModelSpec(min_replicas=0)
        with pytest.raises(ValueError):
            ModelSpec(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            ServingConfig(models={"a": {"replicas": 1}}, default_model="b")

    def test_default_model_resolution(self):
        assert ServingConfig().resolve_default_model() == "default"
        two = ServingConfig(models={"zeta": {"replicas": 1},
                                    "alpha": {"replicas": 1}})
        assert two.resolve_default_model() == "alpha", \
            "first registered name in sorted order"
        pinned = ServingConfig(models={"zeta": {"replicas": 1},
                                       "alpha": {"replicas": 1}},
                               default_model="zeta")
        assert pinned.resolve_default_model() == "zeta"


# ========================================================== fair share
class TestLedgerFairShare:
    def test_charge_is_weight_normalized(self):
        clk = _Clock()
        led = _ledger({"a": {"weight": 1.0}, "b": {"weight": 4.0}}, clk)
        ra, rb = _req(plen=12, max_new=4, tenant="a"), \
            _req(plen=12, max_new=4, tenant="b")
        led.charge(ra)
        led.charge(rb)
        # same 16 tokens; b's virtual service is a quarter of a's (the
        # idle "default" tenant holds the floor at zero — solo history
        # must NOT be re-floored away, see test below)
        ka, kb = led.drain_key("a"), led.drain_key("b")
        assert ka[1] == pytest.approx(16.0)
        assert kb[1] == pytest.approx(4.0)
        assert kb < ka, "weight-4 tenant must drain first"

    def test_solo_flood_banks_service_before_victim_dispatches(self):
        """Regression: the re-floor must range over ALL known tenants
        (idle = 0), not just charged ones — otherwise a lone flooding
        tenant is re-zeroed to parity on every charge and the fair pop
        degrades to FIFO until the starved tenant's first dispatch."""
        clk = _Clock()
        led = _ledger({"a": {}, "b": {}}, clk)
        for _ in range(6):
            led.charge(_req(plen=8, max_new=4, tenant="a"))
        assert led.drain_key("a")[1] == pytest.approx(6 * 12.0)
        assert led.drain_key("b") < led.drain_key("a"), \
            "the never-dispatched tenant must be preferred"

    def test_refloor_keeps_counters_bounded(self):
        clk = _Clock()
        led = _ledger({"a": {}, "b": {}}, clk)
        for _ in range(50):
            # every known tenant (incl. the merged default) charges, so
            # the floor rises each round and counters return to zero
            led.charge(_req(tenant="a"))
            led.charge(_req(tenant="b"))
            led.charge(_req(tenant="default"))
        assert led.drain_key("a")[1] == pytest.approx(0.0)
        assert led.drain_key("b")[1] == pytest.approx(0.0)
        assert led.drain_key("default")[1] == pytest.approx(0.0)

    def test_known_and_names(self):
        led = _ledger({"a": {}})
        assert led.known("a") and led.known("default")
        assert not led.known("ghost")
        assert led.tenant_names == ["a", "default"]


# =============================================================== quota
class TestLedgerQuota:
    def test_token_rate_edge_fires_once_and_clears(self):
        clk = _Clock()
        jr = _FakeJournal()
        m = serving_metrics(("interactive", "batch"), tenants=("a", "default"))
        # 2 tokens/s over a 10 s window = 20-token budget
        led = _ledger({"a": {"token_rate": 2.0}}, clk, journal=jr, metrics=m)
        led.charge(_req(plen=8, max_new=4, tenant="a"))   # 12 tokens: under
        assert not led.over_quota("a")
        led.charge(_req(plen=8, max_new=4, tenant="a"))   # 24 tokens: over
        assert led.over_quota("a")
        assert m.snapshot()["tenant_over_quota_a"] == 1.0
        led.charge(_req(plen=8, max_new=4, tenant="a"))   # still over
        assert [k for k, _ in jr.events] == ["tenant_throttled"], \
            "throttle journal event must fire on the EDGE, not per charge"
        assert jr.events[0][1] == {"tenant": "a", "reason": "token_rate"}
        # window ages out with zero traffic -> quota clears on reconcile
        clk.t += 11.0
        led.reconcile()
        assert not led.over_quota("a")
        assert m.snapshot()["tenant_over_quota_a"] == 0.0
        # next flood re-fires the edge
        for _ in range(3):
            led.charge(_req(plen=8, max_new=4, tenant="a"))
        assert len(jr.events) == 2

    def test_unlimited_tenant_never_over_quota(self):
        clk = _Clock()
        led = _ledger({"a": {}}, clk)
        for _ in range(100):
            led.charge(_req(plen=32, max_new=32, tenant="a"))
        assert not led.over_quota("a")

    def test_victim_rank_prefers_over_quota_tenant(self):
        clk = _Clock()
        led = _ledger({"a": {"token_rate": 1.0}, "b": {}}, clk)
        for _ in range(5):
            led.charge(_req(plen=8, max_new=4, tenant="a"))
        assert led.victim_rank(_req(tenant="a")) == 1
        assert led.victim_rank(_req(tenant="b")) == 0


# =========================================================== KV budget
class TestLedgerKVBudget:
    def test_kv_blocks_projection(self):
        r = _req(plen=17, max_new=6)
        # ceil((17 + 6) / 8) = 3 blocks, whole-sequence projection
        assert kv_blocks_for(r, 8) == 3
        assert kv_blocks_for(r, 16) == 2

    def test_budget_admits_charges_and_releases(self):
        clk = _Clock()
        led = _ledger({"a": {"kv_block_budget": 4}}, clk)
        rep = _FakeReplica(0)
        r1 = _req(plen=17, max_new=6, tenant="a")     # 3 blocks
        r2 = _req(plen=17, max_new=6, tenant="a")     # 3 blocks
        assert led.admits_kv(r1, rep)
        led.charge_kv(r1, rep)
        assert not led.admits_kv(r2, rep), "3 + 3 > budget of 4"
        # refusal surfaces as a kv_budget throttle state
        assert led.snapshot()["a"]["throttled"] == "kv_budget"
        led.release_kv(r1.uid)
        assert led.admits_kv(r2, rep)
        assert led.snapshot()["a"]["throttled"] is None

    def test_charge_is_idempotent_per_uid(self):
        clk = _Clock()
        led = _ledger({"a": {"kv_block_budget": 4}}, clk)
        rep0, rep1 = _FakeReplica(0), _FakeReplica(1)
        r = _req(plen=17, max_new=6, tenant="a")      # 3 blocks
        led.charge_kv(r, rep0)
        led.charge_kv(r, rep1)    # failover re-dispatch: refunds rep0
        books = led.snapshot()["a"]["kv_blocks_used"]
        assert books == {1: 3}, books

    def test_reconcile_releases_done_requests(self):
        clk = _Clock()
        led = _ledger({"a": {"kv_block_budget": 4}}, clk)
        rep = _FakeReplica(0)
        r = _req(plen=17, max_new=6, tenant="a")
        led.charge_kv(r, rep)
        led.reconcile()
        assert led.snapshot()["a"]["kv_blocks_used"] == {0: 3}, \
            "live request must stay charged across reconcile"
        r.finish(RequestState.FINISHED, FinishReason.LENGTH)
        led.reconcile()
        assert led.snapshot()["a"]["kv_blocks_used"] == {}

    def test_unlimited_budget_is_a_noop(self):
        led = _ledger({"a": {}}, _Clock())
        rep = _FakeReplica(0)
        r = _req(tenant="a")
        assert led.admits_kv(r, rep)
        led.charge_kv(r, rep)
        assert led.snapshot()["a"]["kv_blocks_used"] == {}


# ======================================================= queue ordering
class TestQueueFairOrdering:
    def _drain(self, q, led=None):
        """Pop-and-charge loop, the router's dispatch contract."""
        out = []
        while len(q):
            r = q.pop(timeout=0.1)
            if r is None:
                break
            if led is not None:
                led.charge(r)
            out.append(r)
        return out

    def test_fair_pop_interleaves_flooded_tenant(self):
        clk = _Clock()
        led = _ledger({"a": {"weight": 1.0}, "b": {"weight": 4.0}}, clk)
        q = AdmissionQueue(64, tenancy=led)
        flood = [_req(tenant="a") for _ in range(6)]
        inter = [_req(tenant="b") for _ in range(2)]
        for r in flood + inter:
            q.offer(r)
        order = self._drain(q, led)
        pos = [i for i, r in enumerate(order) if r.tenant == "b"]
        # DWF: a1 pops at parity, then b overtakes until its quarter-
        # rate service catches up — both b entries drain in the first
        # three pops despite six earlier-submitted a entries
        assert pos == [1, 2], [r.tenant for r in order]
        assert len(order) == 8

    def test_tenancy_off_pop_is_historical_fifo(self):
        q = AdmissionQueue(64)
        reqs = [_req(tenant="a") for _ in range(4)] + \
            [_req(tenant="b") for _ in range(2)]
        for r in reqs:
            q.offer(r)
        order = self._drain(q)
        assert [r.uid for r in order] == [r.uid for r in reqs], \
            "without a ledger the pop order must stay uid-FIFO"

    def test_over_quota_tenant_deprioritized_but_work_conserving(self):
        clk = _Clock()
        led = _ledger({"a": {"token_rate": 1.0}, "b": {}}, clk)
        for _ in range(5):                       # drive a over its quota
            led.charge(_req(plen=8, max_new=4, tenant="a"))
        assert led.over_quota("a")
        q = AdmissionQueue(64, tenancy=led)
        ra = [_req(tenant="a") for _ in range(2)]
        rb = [_req(tenant="b") for _ in range(2)]
        for r in ra + rb:
            q.offer(r)
        order = self._drain(q, led)
        assert [r.tenant for r in order] == ["b", "b", "a", "a"], \
            "in-quota tenant first; over-quota still drains when alone"

    def test_victim_key_sheds_over_quota_tenant_first(self):
        clk = _Clock()
        led = _ledger({"a": {"token_rate": 1.0}, "b": {}}, clk)
        for _ in range(5):
            led.charge(_req(plen=8, max_new=4, tenant="a"))
        q = AdmissionQueue(64, tenancy=led)
        # batch-class b (shed_rank 1) vs interactive over-quota a: the
        # over-quota component leads, beating the class shed rank
        va = q._victim_key(_req(tenant="a"))
        vb = q._victim_key(_req(tenant="b", request_class="batch",
                                shed_rank=1))
        assert va > vb, (va, vb)
        q_off = AdmissionQueue(64)
        r = _req(tenant="a")
        assert q_off._victim_key(r) == (0,) + tuple(r.shed_key), \
            "tenancy off must prepend a constant 0 (historical order)"

    def test_per_tenant_shed_counter(self):
        clk = _Clock()
        led = _ledger({"a": {}}, clk)
        m = serving_metrics(("interactive", "batch"),
                            tenants=("a", "default"))
        q = AdmissionQueue(1, metrics=m, tenancy=led)
        q.offer(_req(tenant="a"))
        with pytest.raises(Exception):
            q.offer(_req(tenant="a"))            # depth 1: shed
        snap = m.snapshot()
        assert snap["requests_shed_tenant_a"] == 1.0
        assert snap["requests_shed"] == 1.0


# ============================================================ SLO rules
class TestTenantSLORules:
    def test_per_tenant_rules_derived(self):
        cfg = SLOConfig(
            enabled=True,
            tenants={"alpha": SLOClassTarget(ttft_p95_ms=250.0,
                                             tpot_p95_ms=50.0,
                                             availability=0.99)})
        eng = AlertEngine(cfg, windowed=None)
        by_name = {r.name: r for r in eng.rules}
        ttft = by_name["slo_ttft_tenant_alpha"]
        assert ttft.scope == "tenant"
        assert ttft.metric == "ttft_s_tenant_alpha"
        assert ttft.threshold_s == pytest.approx(0.25)
        avail = by_name["slo_availability_tenant_alpha"]
        assert avail.metric == "requests_shed_tenant_alpha"
        assert avail.denominator == "requests_submitted_tenant_alpha", \
            "a tenant's burn must be measured against ITS traffic only"
        st = eng.status()["slo_availability_tenant_alpha"]
        assert st["scope"] == "tenant" and st["firing"] is False

    def test_no_tenant_targets_no_tenant_rules(self):
        eng = AlertEngine(SLOConfig(enabled=True), windowed=None)
        assert not [r for r in eng.rules if r.scope == "tenant"]


# ============================================================= frontend
def _fe(tenants=None, models=None, engines=None, **scfg):
    cfg = {"max_queue_depth": 64}
    if tenants is not None:
        cfg["tenants"] = tenants
    if models is not None:
        cfg["models"] = models
    cfg.update(scfg)
    if engines is None:
        engines = [] if models is not None else [tiny_engine()]
    return ServingFrontend(engines, ServingConfig(**cfg))


class TestFrontendTenancy:
    TEN = {"alpha": {"weight": 1.0}, "bravo": {"weight": 4.0}}

    def test_unknown_tenant_refused_before_counters(self):
        fe = _fe(tenants=self.TEN)
        try:
            before = fe.metrics.snapshot()["requests_submitted"]
            with pytest.raises(ValueError, match="unknown tenant"):
                fe.submit([1] * 8, max_new_tokens=2, tenant="ghost")
            snap = fe.metrics.snapshot()
            assert snap["requests_submitted"] == before, \
                "caller bugs must not count as submitted traffic (PR 8)"
            assert snap["requests_shed"] == 0.0
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_unknown_model_refused_before_counters(self):
        fe = _fe()
        try:
            with pytest.raises(ValueError, match="unknown model"):
                fe.submit([1] * 8, max_new_tokens=2, model="ghost")
            assert fe.metrics.snapshot()["requests_submitted"] == 0.0
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_legacy_submit_signature_untouched(self):
        """Call sites that predate tenancy keep working verbatim, and
        with tenancy OFF the metrics namespace is byte-identical — no
        tenant series leak into the historical snapshot."""
        fe = _fe()
        try:
            h = fe.submit([1, 2, 3, 4], max_new_tokens=3)
            assert fe.wait_all([h], timeout=120)
            assert len([e.token for e in h.drain()]) == 3
            assert not [k for k in fe.metrics.snapshot() if "tenant" in k]
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_per_tenant_series_and_health_report(self):
        fe = _fe(tenants=self.TEN)
        try:
            hs = [fe.submit([1] * 8, max_new_tokens=2, tenant=t)
                  for t in ("alpha", "bravo", "bravo")]
            assert fe.wait_all(hs, timeout=120)
            snap = fe.metrics.snapshot()
            assert snap["requests_submitted_tenant_alpha"] == 1.0
            assert snap["requests_submitted_tenant_bravo"] == 2.0
            assert snap["tenant_over_quota_alpha"] == 0.0
            report = fe.health_report()
            books = report["tenants"]
            assert set(books) == {"alpha", "bravo", "default"}
            assert books["bravo"]["weight"] == 4.0
            assert books["bravo"]["window_tokens"] == pytest.approx(20.0)
            text = fe.health_report_text()
            assert "tenant bravo:" in text
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_flood_isolation_on_vs_starvation_off(self):
        """THE fairness claim, deterministically: a serial engine
        (max_ragged_sequence_count=1) makes dispatch order equal queue
        order, and ``admitted_t`` records it without timing noise. With
        DWF on, tenant bravo's interactive pair overtakes tenant
        alpha's six-deep flood; with tenancy off the same traffic
        drains FIFO and bravo goes last — the starvation the feature
        exists to prevent."""
        for tenants, expect_overtake in ((self.TEN, True), (None, False)):
            fe = _fe(tenants=tenants, engines=[
                tiny_engine(max_ragged_sequence_count=1)])
            try:
                kw = {"tenant": "alpha"} if tenants else {}
                flood = [fe.submit([1] * 8, max_new_tokens=2, **kw)
                         for _ in range(6)]
                kw = {"tenant": "bravo"} if tenants else {}
                inter = [fe.submit([2] * 8, max_new_tokens=2, **kw)
                         for _ in range(2)]
                assert fe.wait_all(flood + inter, timeout=300)
                ranked = sorted(flood + inter,
                                key=lambda h: h._req.admitted_t)
                pos = [i for i, h in enumerate(ranked) if h in inter]
                if expect_overtake:
                    assert max(pos) <= 3, \
                        f"fair-on: bravo admitted at {pos}, starved"
                    # work conservation: the flood still finished
                    assert all(len(h.drain()) == 2 for h in flood)
                else:
                    assert pos == [6, 7], \
                        f"tenancy-off FIFO should starve bravo, got {pos}"
            finally:
                fe.shutdown(drain=False, timeout=5)


class TestFrontendMultiModel:
    MODELS = {
        "fam_a": {"model": MODEL_KW, "engine": ENGINE_KW, "seed": 0,
                  "replicas": 1},
        "fam_b": {"model": dict(MODEL_KW, hidden_size=48,
                                intermediate_size=96),
                  "engine": ENGINE_KW, "seed": 7, "replicas": 1},
    }

    def test_requests_route_to_their_own_pool(self):
        fe = _fe(models=self.MODELS)
        try:
            by_id = {r.replica_id: getattr(r, "model_id", "default")
                     for r in fe.router.replicas}
            assert sorted(by_id.values()) == ["fam_a", "fam_b"]
            hs = {m: [fe.submit([3] * 8, max_new_tokens=2, model=m)
                      for _ in range(3)]
                  for m in ("fam_a", "fam_b")}
            assert fe.wait_all(hs["fam_a"] + hs["fam_b"], timeout=300)
            for want, handles in hs.items():
                for h in handles:
                    assert by_id[h._req.replica_id] == want, \
                        f"request for {want} ran on " \
                        f"{by_id[h._req.replica_id]}"
            report = fe.health_report()
            assert sorted(r["model"] for r in report["replicas"]) == \
                ["fam_a", "fam_b"]
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_default_model_used_when_caller_names_none(self):
        fe = _fe(models=self.MODELS, default_model="fam_b")
        try:
            h = fe.submit([3] * 8, max_new_tokens=2)
            assert fe.wait_all([h], timeout=300)
            assert h._req.model_id == "fam_b"
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_engine_factories_win_over_spec(self):
        built = []

        def fac():
            built.append(True)
            return tiny_engine()

        fe = ServingFrontend(
            [], ServingConfig(max_queue_depth=64,
                              models={"fam_a": {"replicas": 1}}),
            model_engine_factories={"fam_a": fac})
        try:
            assert built == [True]
            h = fe.submit([3] * 8, max_new_tokens=2)
            assert fe.wait_all([h], timeout=300)
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_spec_with_no_model_and_no_factory_refused(self):
        with pytest.raises(ValueError):
            ServingFrontend(
                [], ServingConfig(models={"fam_a": {"replicas": 1}}))
