"""MiCS tests (reference tests/unit/runtime/zero/test_mics_optimizer.py):
mics_shard_size shards params over a sub-group (the fsdp mesh axis) and
replicates across the data axis, instead of sharding over the full DP world
(runtime/zero/mics.py:55)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import build_model


def tiny_data(n=64, seq=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(n, seq + 1),
                                      dtype=np.int64)}


def make_config(shard_size, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "mics_shard_size": shard_size},
        "mesh": {"data": -1, "fsdp": 1},
        "steps_per_print": 100,
    }
    cfg.update(over)
    return cfg


def run_steps(engine, data, steps=4):
    loader = deepspeed_tpu.runtime.dataloader.RepeatingLoader(
        engine.deepspeed_io(data))
    it = iter(loader)
    losses = []
    for _ in range(steps):
        loss = engine(next(it))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def test_mics_shapes_mesh_and_trains(devices8):
    """shard_size=4 on 8 devices → fsdp=4 (shard group) × data=2 (replicas);
    params are sharded over fsdp only, so each shard lives on 2 devices."""
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"), config=make_config(4))
    assert engine.mesh.shape["fsdp"] == 4
    assert engine.mesh.shape["data"] == 2

    # Largest param: sharded over the 4-way group, replicated over data.
    leaves = jax.tree.leaves(engine.state.params)
    big = max(leaves, key=lambda p: p.size)
    spec_axes = {a for axes in big.sharding.spec if axes
                 for a in (axes if isinstance(axes, tuple) else (axes,))}
    assert "fsdp" in spec_axes and "data" not in spec_axes, big.sharding
    # replication factor 2: 4 distinct shards, each held by 2 of 8 devices
    assert len(big.sharding.device_set) == 8
    idx_map = big.sharding.devices_indices_map(big.shape)
    distinct = {tuple((s.start, s.stop) for s in idx) for idx in
                idx_map.values()}
    assert len(distinct) == 4, f"expected 4 distinct shards: {distinct}"

    losses = run_steps(engine, tiny_data(), steps=5)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_mics_requires_stage3(devices8):
    cfg = make_config(4)
    cfg["zero_optimization"]["stage"] = 2
    with pytest.raises(ValueError, match="stage=3"):
        deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)


def test_mics_rejects_conflicting_mesh(devices8):
    cfg = make_config(4, mesh={"data": -1, "fsdp": 2})
    with pytest.raises(ValueError, match="conflicts with the mesh"):
        deepspeed_tpu.initialize(model=build_model("tiny"), config=cfg)
