"""Elastic fleet autoscaling tests (ISSUE 12, docs/SERVING.md "Elastic
autoscaling").

Two layers:

- **Deterministic policy tests**: a :class:`FleetController` driven by a
  fake clock against a fake fleet — hysteresis/cooldown boundaries,
  min/max clamps, shrink-prefers-parked, decode-capability floors,
  re-role flap suppression, proactive-brownout hysteresis. No threads,
  no engines, no sleeps.
- **Live-stack tests** over tiny engines: dynamic membership under
  traffic (add/remove with resident KV — losslessness asserted against
  uncontended greedy references), the supervisor retirement race guard
  (a pending/in-flight restart must never resurrect a removed slot),
  and an end-to-end elastic frontend whose journal matches the
  controller's decision log exactly.
"""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (
    InferenceEngineV2, RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving import (AutoscalerConfig, ServingConfig,
                                   ServingFrontend, serving_metrics)
from deepspeed_tpu.serving.autoscaler import (FleetController, FleetSignals,
                                              ReplicaInfo)
from deepspeed_tpu.serving.router import DECODE_CAPABLE, PREFILL_CAPABLE
from deepspeed_tpu.telemetry import OpsJournal, validate_events

VOCAB = 128

_model = None
_params = None


def tiny_engine(i=0, kv_blocks=64, max_seqs=4):
    global _model, _params
    if _model is None:
        _model = CausalLM(TransformerConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, max_seq_len=256, norm="rmsnorm",
            activation="silu", position="rope"))
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=kv_blocks, kv_block_size=8,
        max_tracked_sequences=32)
    eng = InferenceEngineV2(_model, params=_params, config=vcfg)
    _params = eng.params
    return eng


def prompts(n, seed, lo=8, hi=20):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(length)).tolist()
            for length in rng.integers(lo, hi, size=n)]


# ------------------------------------------------------------ policy layer
class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class FakeFleet:
    """Actuation surface the policy tests drive the controller against."""

    def __init__(self, replicas, disaggregated=False,
                 prefill_cost=1.0, decode_cost=8.0):
        # rid -> dict(role, accepting, parked, pre, dec)
        self.replicas = {rid: dict(spec) for rid, spec in replicas.items()}
        self.next_id = max(replicas) + 1 if replicas else 0
        self.disaggregated = disaggregated
        self.prefill_cost = prefill_cost
        self.decode_cost = decode_cost
        self.queue_depth = 0
        self.burn_slow = 0.0
        self.brownout = None
        self.actions = []

    @staticmethod
    def rep(role="mixed", accepting=True, parked=False, pre=0, dec=0):
        return dict(role=role, accepting=accepting, parked=parked,
                    pre=pre, dec=dec)

    def fleet_signals(self):
        infos = tuple(
            ReplicaInfo(rid, r["role"], r["accepting"], r["parked"],
                        r["pre"], r["dec"])
            for rid, r in sorted(self.replicas.items()))
        return FleetSignals(queue_depth=self.queue_depth, replicas=infos,
                            burn_slow_max=self.burn_slow,
                            prefill_token_cost=self.prefill_cost,
                            decode_token_cost=self.decode_cost,
                            disaggregated=self.disaggregated)

    def add_replica(self, role):
        rid = self.next_id
        self.next_id += 1
        self.replicas[rid] = self.rep(role=role)
        self.actions.append(("add", rid, role))
        return rid

    def remove_replica(self, rid, reason="scale_down"):
        del self.replicas[rid]
        self.actions.append(("remove", rid, reason))
        return True

    def set_replica_role(self, rid, role):
        self.actions.append(("rerole", rid, self.replicas[rid]["role"],
                             role))
        self.replicas[rid]["role"] = role
        return True

    def set_proactive_brownout(self, frac):
        self.brownout = frac
        self.actions.append(("brownout", frac))


def make_controller(fleet, clock, journal=None, **cfg):
    base = dict(enabled=True, min_replicas=1, max_replicas=4,
                scale_up_queue_per_replica=4.0,
                scale_down_queue_per_replica=0.25,
                scale_down_tokens_per_replica=8.0,
                up_stable_ticks=2, down_stable_ticks=3,
                scale_up_cooldown_s=5.0, scale_down_cooldown_s=10.0,
                tick_interval_s=1.0, rerole_ratio=4.0,
                rerole_stable_ticks=3, rerole_cooldown_s=10.0,
                brownout_burn_threshold=2.0, brownout_fraction=0.5)
    base.update(cfg)
    return FleetController(AutoscalerConfig(**base), fleet, clock=clock,
                           journal=journal, async_actions=False)


class TestScalePolicy:
    def test_scale_up_needs_stable_ticks_then_respects_cooldown(self):
        clock = FakeClock()
        fleet = FakeFleet({0: FakeFleet.rep()})
        ctl = make_controller(fleet, clock, up_stable_ticks=2,
                              scale_up_cooldown_s=5.0)
        fleet.queue_depth = 50                      # way past the watermark
        ctl.tick(clock.t)                           # streak 1: no action
        assert len(fleet.replicas) == 1
        ctl.tick(clock.advance(1.0))                # streak 2: scale up
        assert len(fleet.replicas) == 2
        # pressure persists but the up cooldown gates the next step
        for _ in range(4):
            ctl.tick(clock.advance(1.0))            # t = 2..5 (< 1 + 5)
        assert len(fleet.replicas) == 2
        ctl.tick(clock.advance(1.0))                # t = 6: cooled + stable
        assert len(fleet.replicas) == 3
        ups = [d for d in ctl.decision_log if d["action"] == "scale_up"]
        assert len(ups) == 2
        assert all(d["reason"] == "queue_pressure" for d in ups)

    def test_one_calm_tick_resets_the_up_streak(self):
        clock = FakeClock()
        fleet = FakeFleet({0: FakeFleet.rep()})
        ctl = make_controller(fleet, clock, up_stable_ticks=3)
        fleet.queue_depth = 50
        ctl.tick(clock.t)
        ctl.tick(clock.advance(1.0))
        fleet.queue_depth = 0                       # blip clears
        ctl.tick(clock.advance(1.0))
        fleet.queue_depth = 50
        ctl.tick(clock.advance(1.0))
        ctl.tick(clock.advance(1.0))
        assert len(fleet.replicas) == 1             # streak restarted
        ctl.tick(clock.advance(1.0))
        assert len(fleet.replicas) == 2

    def test_max_replicas_clamp(self):
        clock = FakeClock()
        fleet = FakeFleet({i: FakeFleet.rep() for i in range(3)})
        ctl = make_controller(fleet, clock, max_replicas=3,
                              up_stable_ticks=1, scale_up_cooldown_s=0.0)
        fleet.queue_depth = 500
        for _ in range(10):
            ctl.tick(clock.advance(1.0))
        assert len(fleet.replicas) == 3
        assert list(ctl.decision_log) == []

    def test_scale_down_to_min_and_not_past_it(self):
        clock = FakeClock()
        fleet = FakeFleet({i: FakeFleet.rep() for i in range(3)})
        ctl = make_controller(fleet, clock, min_replicas=1,
                              down_stable_ticks=3,
                              scale_down_cooldown_s=4.0)
        # idle fleet: queue empty, no outstanding work
        for _ in range(30):
            ctl.tick(clock.advance(1.0))
        assert len(fleet.replicas) == 1
        downs = [d for d in ctl.decision_log
                 if d["action"] == "scale_down"]
        assert len(downs) == 2
        # cooldown respected between the two removals
        assert downs[1]["t"] - downs[0]["t"] >= 4.0

    def test_below_min_repairs_regardless_of_load(self):
        clock = FakeClock()
        fleet = FakeFleet({0: FakeFleet.rep()})
        ctl = make_controller(fleet, clock, min_replicas=2)
        ctl.tick(clock.t)                           # no streak needed
        assert len(fleet.replicas) == 2
        assert ctl.decision_log[0]["reason"] == "below_min"

    def test_shrink_prefers_parked_slots(self):
        clock = FakeClock()
        fleet = FakeFleet({
            0: FakeFleet.rep(pre=0, dec=0),
            1: FakeFleet.rep(accepting=False, parked=True),
            2: FakeFleet.rep(pre=0, dec=5)})
        ctl = make_controller(fleet, clock, down_stable_ticks=1,
                              scale_down_cooldown_s=0.0,
                              scale_down_tokens_per_replica=100.0)
        ctl.tick(clock.advance(1.0))
        assert 1 not in fleet.replicas              # the corpse went first
        assert len(fleet.replicas) == 2

    def test_shrink_skips_last_decode_capable(self):
        clock = FakeClock()
        fleet = FakeFleet({
            0: FakeFleet.rep(role="prefill", pre=3),
            1: FakeFleet.rep(role="decode", dec=0),     # least loaded!
            2: FakeFleet.rep(role="prefill", pre=9)},
            disaggregated=True)
        ctl = make_controller(fleet, clock, down_stable_ticks=1,
                              scale_down_cooldown_s=0.0,
                              scale_down_queue_per_replica=100.0,
                              scale_down_tokens_per_replica=100.0)
        ctl.tick(clock.advance(1.0))
        # replica 1 is idler but is the only decode-capable: replica 0
        # (least-loaded prefill) is removed instead
        assert 1 in fleet.replicas and 0 not in fleet.replicas

    def test_pressure_at_max_evicts_parked_corpse_then_grows(self):
        """At max_replicas with a parked corpse aboard, sustained queue
        pressure first evicts the corpse (zero-cost seat) and then
        grows live capacity into the freed seat — the fleet is never
        pinned below max by a circuit-broken slot."""
        clock = FakeClock()
        fleet = FakeFleet({
            0: FakeFleet.rep(accepting=False, parked=True),
            1: FakeFleet.rep(), 2: FakeFleet.rep()})
        ctl = make_controller(fleet, clock, max_replicas=3,
                              up_stable_ticks=1,
                              scale_up_cooldown_s=1.0)
        fleet.queue_depth = 100
        ctl.tick(clock.advance(1.0))
        assert 0 not in fleet.replicas          # corpse evicted first
        assert ctl.decision_log[-1]["reason"] == "evict_parked"
        ctl.tick(clock.advance(1.0))            # cooled: grow into seat
        assert len(fleet.replicas) == 3
        assert ctl.decision_log[-1]["action"] == "scale_up"

    def test_grow_role_follows_dominant_phase_load(self):
        clock = FakeClock()
        fleet = FakeFleet({0: FakeFleet.rep(role="prefill", pre=100),
                           1: FakeFleet.rep(role="decode", dec=1)},
                          disaggregated=True, prefill_cost=1.0,
                          decode_cost=8.0)
        ctl = make_controller(fleet, clock, up_stable_ticks=1,
                              scale_up_cooldown_s=0.0)
        fleet.queue_depth = 100
        ctl.tick(clock.advance(1.0))
        assert fleet.actions[-1] == ("add", 2, "prefill")


class TestRerolePolicy:
    def _fleet(self, pre, dec, n_decode=2):
        reps = {0: FakeFleet.rep(role="prefill", pre=pre)}
        for i in range(n_decode):
            reps[1 + i] = FakeFleet.rep(role="decode", dec=dec)
        return FakeFleet(reps, disaggregated=True, prefill_cost=1.0,
                         decode_cost=1.0)

    def test_stable_imbalance_reroles_once_then_cools_down(self):
        clock = FakeClock()
        fleet = self._fleet(pre=100, dec=1)
        ctl = make_controller(fleet, clock, rerole_stable_ticks=3,
                              rerole_cooldown_s=10.0,
                              scale_up_queue_per_replica=1e9)
        for _ in range(2):
            ctl.tick(clock.advance(1.0))
        assert not any(a[0] == "rerole" for a in fleet.actions)
        ctl.tick(clock.advance(1.0))                # 3rd stable tick
        reroles = [a for a in fleet.actions if a[0] == "rerole"]
        # ties on load break toward the NEWEST replica (highest id)
        assert reroles == [("rerole", 2, "decode", "prefill")]
        # imbalance persists, but the cooldown holds the next flip
        for _ in range(8):
            ctl.tick(clock.advance(1.0))
        assert len([a for a in fleet.actions if a[0] == "rerole"]) == 1

    def test_oscillating_imbalance_never_reroles(self):
        clock = FakeClock()
        fleet = self._fleet(pre=100, dec=1)
        ctl = make_controller(fleet, clock, rerole_stable_ticks=2,
                              rerole_cooldown_s=0.0,
                              scale_up_queue_per_replica=1e9)
        for i in range(12):
            # flip the dominant phase every tick: the signed streak
            # resets on every direction change
            pre, dec = (100, 1) if i % 2 == 0 else (1, 100)
            fleet.replicas[0].update(pre=pre, dec=0)
            for rid in (1, 2):
                fleet.replicas[rid].update(dec=dec, pre=0)
            ctl.tick(clock.advance(1.0))
        assert not any(a[0] == "rerole" for a in fleet.actions)

    def test_rerole_never_strands_decode(self):
        clock = FakeClock()
        fleet = self._fleet(pre=100, dec=1, n_decode=1)
        ctl = make_controller(fleet, clock, rerole_stable_ticks=1,
                              rerole_cooldown_s=0.0,
                              scale_up_queue_per_replica=1e9)
        for _ in range(5):
            ctl.tick(clock.advance(1.0))
        # the only decode replica may never flip to prefill
        assert not any(a[0] == "rerole" for a in fleet.actions)


class TestProactiveBrownout:
    def test_activates_before_alert_and_deactivates_with_hysteresis(self):
        clock = FakeClock()
        fleet = FakeFleet({0: FakeFleet.rep()})
        journal = OpsJournal(capacity=64)
        ctl = make_controller(fleet, clock, journal=journal,
                              brownout_burn_threshold=2.0,
                              brownout_fraction=0.5)
        fleet.burn_slow = 1.9
        ctl.tick(clock.advance(1.0))
        assert fleet.brownout is None               # below threshold
        fleet.burn_slow = 2.1
        ctl.tick(clock.advance(1.0))
        assert fleet.brownout == 0.5                # proactive, pre-breach
        fleet.burn_slow = 1.5                       # above thr/2: held
        ctl.tick(clock.advance(1.0))
        assert fleet.brownout == 0.5
        fleet.burn_slow = 0.9                       # below thr/2: released
        ctl.tick(clock.advance(1.0))
        assert fleet.brownout is None
        evs = journal.events(kinds=("brownout_proactive",))
        assert [e["detail"]["active"] for e in evs] == [True, False]
        assert validate_events(journal.events()) == []


class TestConfigValidation:
    def test_min_replicas_floor(self):
        with pytest.raises(Exception, match="min_replicas"):
            AutoscalerConfig(min_replicas=0)

    def test_max_at_least_min(self):
        with pytest.raises(Exception, match="max_replicas"):
            AutoscalerConfig(min_replicas=3, max_replicas=2)

    def test_brownout_fraction_range(self):
        with pytest.raises(Exception, match="brownout_fraction"):
            AutoscalerConfig(brownout_fraction=0.0)

    def test_role_constants_agree_with_router(self):
        from deepspeed_tpu.serving.autoscaler import _DECODE_CAPABLE

        assert tuple(_DECODE_CAPABLE) == tuple(DECODE_CAPABLE)
        assert set(PREFILL_CAPABLE) == {"prefill", "mixed"}


# ------------------------------------------------------- metrics satellite
class TestFleetShapeObservability:
    def test_registry_predeclares_fleet_gauges(self):
        names = serving_metrics().names()
        for g in ("replicas_target", "replicas_role_prefill",
                  "replicas_role_decode", "replicas_role_mixed",
                  "brownout_proactive_active"):
            assert g in names["gauges"], g
        assert "requests_evacuated" in names["counters"]

    def test_role_census_and_gauges_live(self):
        scfg = ServingConfig(
            max_queue_depth=16,
            disaggregation={"enabled": True,
                            "roles": ["prefill", "decode"]})
        fe = ServingFrontend([tiny_engine(0), tiny_engine(1)], scfg)
        try:
            assert fe.router.role_census() == {"prefill": 1, "decode": 1,
                                               "mixed": 0}
            hs = [fe.submit(p, max_new_tokens=3) for p in prompts(3, 0)]
            assert fe.wait_all(hs, timeout=120)
            deadline = time.monotonic() + 10
            snap = {}
            while time.monotonic() < deadline:
                snap = fe.metrics_snapshot()
                if snap.get("replicas_role_prefill") == 1.0:
                    break
                time.sleep(0.02)
            assert snap["replicas_role_prefill"] == 1.0
            assert snap["replicas_role_decode"] == 1.0
            assert snap["replicas_role_mixed"] == 0.0
            assert snap["replicas_target"] == 2.0
        finally:
            fe.shutdown(drain=False, timeout=5)


# ------------------------------------------------------------- live stack
class TestDynamicMembership:
    def test_add_replica_serves_traffic(self):
        fe = ServingFrontend([tiny_engine(0)],
                             ServingConfig(max_queue_depth=64),
                             engine_factory=tiny_engine)
        try:
            rid = fe.add_replica()
            assert rid == 1
            assert len(fe.router.replicas) == 2
            hs = [fe.submit(p, max_new_tokens=4) for p in prompts(8, 1)]
            assert fe.wait_all(hs, timeout=300)
            snap = fe.metrics_snapshot()
            assert snap["requests_completed"] == 8
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_remove_last_replica_impossible(self):
        fe = ServingFrontend([tiny_engine(0)],
                             ServingConfig(max_queue_depth=16))
        try:
            with pytest.raises(ValueError, match="last"):
                fe.remove_replica(0)
            assert len(fe.router.replicas) == 1
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_autoscaler_requires_engine_factory(self):
        with pytest.raises(ValueError, match="engine_factory"):
            ServingFrontend([tiny_engine(0)], ServingConfig(
                max_queue_depth=16, autoscaler={"enabled": True}))

    def test_remove_busy_replica_is_lossless(self):
        """Drain-with-resident-KV (the acceptance criterion): removing
        a replica with in-flight sequences mid-decode completes them
        losslessly on the survivor — every stream byte-identical to an
        uncontended greedy run — with at least one request actually
        evacuated (KV export + staged re-import or re-prefill)."""
        ps = prompts(6, 7, lo=10, hi=16)
        max_new = 48
        # uncontended greedy reference, one sequence at a time
        ref_sched = ContinuousBatchingScheduler(tiny_engine(90))
        ref = []
        for i, p in enumerate(ps):
            ref_sched.submit(500 + i, p, max_new_tokens=max_new)
            ref_sched.run_to_completion()
            ref.append(ref_sched.finished[500 + i].generated)

        fe = ServingFrontend([tiny_engine(0), tiny_engine(1)],
                             ServingConfig(max_queue_depth=64),
                             engine_factory=tiny_engine)
        try:
            hs = [fe.submit(p, max_new_tokens=max_new) for p in ps]
            # wait until replica 0 is genuinely mid-flight, then pull it
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                r0 = fe.router.replica_by_id(0)
                if r0 is not None and len(r0._active) > 0 and \
                        fe.metrics.counter("tokens_generated").value > 0:
                    break
                time.sleep(0.002)
            fe.remove_replica(0)
            assert len(fe.router.replicas) == 1
            assert fe.wait_all(hs, timeout=300)
            gens = [[ev.token for ev in h.drain()] for h in hs]
            assert gens == ref, "evacuation broke greedy byte-parity"
            snap = fe.metrics_snapshot()
            assert snap["requests_evacuated"] >= 1
            assert snap["requests_completed"] == len(ps)
            assert snap["requests_failed"] == 0
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_rerole_live_flips_scheduler_shape(self):
        scfg = ServingConfig(
            max_queue_depth=32,
            disaggregation={"enabled": True,
                            "roles": ["prefill", "decode"],
                            "handoff": {"enabled": True}})
        fe = ServingFrontend([tiny_engine(0), tiny_engine(1)], scfg,
                             engine_factory=tiny_engine)
        try:
            hs = [fe.submit(p, max_new_tokens=4) for p in prompts(4, 3)]
            assert fe.wait_all(hs, timeout=300)
            assert fe.set_replica_role(0, "mixed") is True
            r0 = fe.router.replica_by_id(0)
            assert r0.role == "mixed"
            assert r0.scheduler.prefill_only is False
            assert fe.router.role_census()["mixed"] == 1
            # the re-roled fleet still serves, losslessly
            hs = [fe.submit(p, max_new_tokens=4) for p in prompts(4, 5)]
            assert fe.wait_all(hs, timeout=300)
            assert fe.set_replica_role(0, "mixed") is False  # no-op
        finally:
            fe.shutdown(drain=False, timeout=5)


class TestRetirementRaceGuard:
    def test_retire_cancels_pending_restart(self):
        """A slot whose restart is still in backoff is retired: the
        restart must never fire — no replacement, no journal event, no
        resurrected capacity (the PR 5 shutdown-race guard extended to
        per-slot retirement)."""
        scfg = ServingConfig(
            max_queue_depth=32,
            fault_tolerance={"enabled": True, "restart_backoff_s": 0.4,
                             "restart_backoff_jitter": 0.0,
                             "supervisor_poll_s": 0.01,
                             "max_restarts_in_window": 10},
            faults={"enabled": True, "schedule": [
                {"kind": "crash", "replica": 0, "at_step": 0}]})
        fe = ServingFrontend([tiny_engine(0), tiny_engine(1)], scfg,
                             engine_factory=tiny_engine)
        try:
            hs = []
            for p in prompts(6, 11):
                try:
                    hs.append(fe.submit(p, max_new_tokens=4))
                except Exception:
                    pass
            # wait for the crash to be noticed (restart scheduled)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if fe.supervisor.recovery_pending():
                    break
                time.sleep(0.005)
            assert fe.supervisor.recovery_pending()
            fe.remove_replica(0)                 # retires the slot
            assert not fe.supervisor.recovery_pending()
            fe.wait_all(hs, timeout=120)
            time.sleep(1.0)                      # past the backoff
            assert fe.router.replica_by_id(0) is None
            assert len(fe.router.replicas) == 1
            assert fe.supervisor.restart_log == []
            assert fe.journal.count("replica_restart") == 0
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_retire_mid_build_drops_replacement(self):
        """Retirement landing while the restart's engine build is IN
        FLIGHT: the finished replacement must be dropped, never
        installed or started."""
        release = threading.Event()
        building = threading.Event()

        def slow_factory(i):
            building.set()
            assert release.wait(30)
            return tiny_engine(i)

        scfg = ServingConfig(
            max_queue_depth=32,
            fault_tolerance={"enabled": True, "restart_backoff_s": 0.01,
                             "restart_backoff_jitter": 0.0,
                             "supervisor_poll_s": 0.01,
                             "max_restarts_in_window": 10},
            faults={"enabled": True, "schedule": [
                {"kind": "crash", "replica": 0, "at_step": 0}]})
        fe = ServingFrontend([tiny_engine(0), tiny_engine(1)], scfg,
                             engine_factory=slow_factory)
        try:
            for p in prompts(4, 13):
                try:
                    fe.submit(p, max_new_tokens=3)
                except Exception:
                    pass
            assert building.wait(30), "restart build never started"
            fe.remove_replica(0)                 # retire mid-build
            release.set()
            time.sleep(0.5)                      # let the build finish
            assert fe.router.replica_by_id(0) is None
            assert len(fe.router.replicas) == 1
            assert fe.supervisor.restart_log == []
            assert not any(t.name == "serving-replica-0" and t.is_alive()
                           for t in threading.enumerate())
        finally:
            release.set()
            fe.shutdown(drain=False, timeout=5)


class TestElasticEndToEnd:
    def test_controller_scales_up_and_down_with_journal_parity(self):
        """A 1-replica fleet under a queue burst grows; once idle it
        shrinks back to min. Journal events match the controller's
        decision log exactly — one scale_up/scale_down event per
        completed action."""
        scfg = ServingConfig(
            max_queue_depth=256,
            autoscaler={"enabled": True, "min_replicas": 1,
                        "max_replicas": 3,
                        "scale_up_queue_per_replica": 2.0,
                        "scale_down_queue_per_replica": 0.25,
                        "scale_down_tokens_per_replica": 1.0,
                        "up_stable_ticks": 1, "down_stable_ticks": 2,
                        "scale_up_cooldown_s": 0.1,
                        "scale_down_cooldown_s": 0.2,
                        "tick_interval_s": 0.05})
        fe = ServingFrontend(
            [tiny_engine(0, max_seqs=2)],
            scfg, engine_factory=lambda i: tiny_engine(i, max_seqs=2))
        try:
            hs = [fe.submit(p, max_new_tokens=24)
                  for p in prompts(24, 17)]
            assert fe.wait_all(hs, timeout=600)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                stats = fe.autoscaler.stats()
                if stats["scale_ups"] >= 1 and \
                        len(fe.router.replicas) == 1:
                    break
                time.sleep(0.05)
            stats = fe.autoscaler.stats()
            assert stats["scale_ups"] >= 1, "burst never grew the fleet"
            assert stats["scale_downs"] >= 1, "idle never shrank it"
            assert len(fe.router.replicas) == 1
            assert stats["peak_replicas"] >= 2
            assert stats["replica_seconds"] > 0
            # journal <-> decision-log exact-once parity
            log = [d for d in fe.autoscaler.decision_log
                   if d["action"] in ("scale_up", "scale_down")]
            evs = fe.journal.events(kinds=("scale_up", "scale_down"))
            assert [(e["kind"], e["detail"]["replica"]) for e in evs] \
                == [(d["action"], d["replica"]) for d in log]
            assert validate_events(fe.journal.events()) == []
            snap = fe.metrics_snapshot()
            assert snap["requests_completed"] == 24
            # the actuation surface reaches the health report too
            rep = fe.health_report()
            assert rep["autoscaler"]["scale_ups"] >= 1
            assert rep["autoscaler"]["replicas_target"] == 1.0
            assert "autoscaler: target=1" in fe.health_report_text()
        finally:
            fe.shutdown(drain=False, timeout=5)
