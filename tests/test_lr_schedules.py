"""LR schedule tests (reference tests/unit/runtime/test_lr_schedulers.py)."""

import numpy as np

from deepspeed_tpu.runtime.lr_schedules import (
    build_schedule, warmup_lr, warmup_decay_lr, warmup_cosine_lr, one_cycle,
    lr_range_test, LRSchedulerShim)


def test_warmup_reaches_max_and_holds():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.01, warmup_num_steps=100)
    assert float(s(0)) < 0.001
    np.testing.assert_allclose(float(s(100)), 0.01, rtol=1e-5)
    np.testing.assert_allclose(float(s(1000)), 0.01, rtol=1e-5)


def test_warmup_linear():
    s = warmup_lr(0.0, 0.01, 100, warmup_type="linear")
    np.testing.assert_allclose(float(s(50)), 0.005, rtol=1e-5)


def test_warmup_decay_hits_zero():
    s = warmup_decay_lr(total_num_steps=1000, warmup_max_lr=0.01,
                        warmup_num_steps=100)
    np.testing.assert_allclose(float(s(100)), 0.01, rtol=1e-4)
    assert float(s(1000)) < 1e-8
    assert float(s(550)) == np.testing.assert_allclose(
        float(s(550)), 0.005, rtol=1e-3) or True


def test_warmup_cosine():
    s = warmup_cosine_lr(total_num_steps=1000, warmup_num_steps=100, lr=0.01)
    np.testing.assert_allclose(float(s(100)), 0.01, rtol=1e-4)
    assert float(s(1000)) < 0.01 * 0.01  # cos_min_ratio plus epsilon
    mid = float(s(550))
    assert 0.004 < mid < 0.006


def test_one_cycle_shape():
    s = one_cycle(cycle_min_lr=0.001, cycle_max_lr=0.01,
                  cycle_first_step_size=100)
    np.testing.assert_allclose(float(s(100)), 0.01, rtol=1e-5)
    np.testing.assert_allclose(float(s(0)), 0.001, rtol=1e-5)
    np.testing.assert_allclose(float(s(200)), 0.001, rtol=1e-5)


def test_lr_range_test_growth():
    s = lr_range_test(lr_range_test_min_lr=0.001, lr_range_test_step_size=100,
                      lr_range_test_step_rate=1.0)
    assert float(s(200)) > float(s(100)) > float(s(0))


def test_builder_and_shim():
    shim = LRSchedulerShim(build_schedule("WarmupLR",
                                          {"warmup_max_lr": 0.1,
                                           "warmup_num_steps": 10}))
    shim.step()
    assert shim.get_lr()[0] > 0
    sd = shim.state_dict()
    shim2 = LRSchedulerShim(build_schedule("WarmupLR", {}))
    shim2.load_state_dict(sd)
    assert shim2.last_step == 1
