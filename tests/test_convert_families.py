"""HF checkpoint import — extended model families (reference
module_inject/containers/*: OPT, GPT-NeoX, BLOOM, Falcon, plus Qwen2 from
inference v2): logits pinned against the transformers torch forward for
each family, covering qkv-bias, parallel residual, partial rotary, ALiBi,
embedding LayerNorm, relu/exact-gelu, and interleaved fused-QKV layouts."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import config_from_hf, from_pretrained

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _hf_logits(model, tokens: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        return model(torch.tensor(tokens)).logits.float().numpy()


def _save(model, tmp_path_factory, name):
    path = tmp_path_factory.mktemp(name)
    model.save_pretrained(path, safe_serialization=True)
    return str(path)


def _parity(path, hf_model, vocab, seq=12, atol=4e-4):
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, vocab, size=(2, seq))
    ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=4e-4)
    return model


def test_qwen2_forward_parity(tmp_path_factory):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    cfg = Qwen2Config(vocab_size=120, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = Qwen2ForCausalLM(cfg).eval()
    # HF zero-inits nothing here, but force nonzero qkv biases so the
    # qkv_bias path is actually exercised
    with torch.no_grad():
        for layer in hf.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.uniform_(-0.5, 0.5)
    path = _save(hf, tmp_path_factory, "qwen2")
    model = _parity(path, hf, 120)
    assert model.cfg.qkv_bias


def test_opt_forward_parity(tmp_path_factory):
    from transformers import OPTConfig, OPTForCausalLM

    cfg = OPTConfig(vocab_size=100, hidden_size=32, ffn_dim=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64, do_layer_norm_before=True,
                    activation_function="relu", word_embed_proj_dim=32)
    torch.manual_seed(1)
    hf = OPTForCausalLM(cfg).eval()
    with torch.no_grad():   # exercise every bias path with nonzero values
        for p in hf.parameters():
            if p.ndim == 1:
                p.uniform_(-0.3, 0.3)
    path = _save(hf, tmp_path_factory, "opt")
    model = _parity(path, hf, 100)
    assert model.cfg.activation == "relu"
    assert model.cfg.position == "learned"


def test_gpt_neox_forward_parity(tmp_path_factory):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    cfg = GPTNeoXConfig(vocab_size=110, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=64, rotary_pct=0.5,
                        use_parallel_residual=True)
    torch.manual_seed(2)
    hf = GPTNeoXForCausalLM(cfg).eval()
    with torch.no_grad():
        for p in hf.parameters():
            if p.ndim == 1:
                p.uniform_(-0.3, 0.3)
    path = _save(hf, tmp_path_factory, "neox")
    model = _parity(path, hf, 110)
    assert model.cfg.parallel_residual
    assert model.cfg.rope_pct == 0.5
    assert model.cfg.rot_dim == 4      # head_dim 8 × 0.5


def test_gpt_neox_sequential_residual(tmp_path_factory):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    cfg = GPTNeoXConfig(vocab_size=90, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=64,
                        use_parallel_residual=False)
    torch.manual_seed(3)
    hf = GPTNeoXForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "neox_seq")
    model = _parity(path, hf, 90)
    assert not model.cfg.parallel_residual


def test_bloom_forward_parity(tmp_path_factory):
    from transformers import BloomConfig, BloomForCausalLM

    cfg = BloomConfig(vocab_size=130, hidden_size=32, n_layer=2, n_head=4)
    torch.manual_seed(4)
    hf = BloomForCausalLM(cfg).eval()
    with torch.no_grad():
        for p in hf.parameters():
            if p.ndim == 1:
                p.uniform_(-0.3, 0.3)
    path = _save(hf, tmp_path_factory, "bloom")
    model = _parity(path, hf, 130)
    assert model.cfg.position == "alibi"
    assert model.cfg.embedding_layernorm


@pytest.mark.parametrize("new_arch", [False, True])
def test_falcon_forward_parity(tmp_path_factory, new_arch):
    from transformers import FalconConfig, FalconForCausalLM

    kwargs = dict(vocab_size=105, hidden_size=32, num_hidden_layers=2,
                  num_attention_heads=4, bias=False, parallel_attn=True,
                  alibi=False, max_position_embeddings=64)
    if new_arch:
        kwargs.update(new_decoder_architecture=True, num_kv_heads=2)
    else:
        kwargs.update(new_decoder_architecture=False, multi_query=True)
    cfg = FalconConfig(**kwargs)
    torch.manual_seed(5)
    hf = FalconForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, f"falcon{int(new_arch)}")
    model = _parity(path, hf, 105)
    assert model.cfg.parallel_residual
    assert model.cfg.kv_heads == (2 if new_arch else 1)


def test_kv_cache_generate_matches_forward_alibi(tmp_path_factory):
    """ALiBi decode path: prefill+decode logits must match the plain
    forward at each position (BLOOM serving path)."""
    from transformers import BloomConfig, BloomForCausalLM

    cfg = BloomConfig(vocab_size=80, hidden_size=32, n_layer=2, n_head=4)
    torch.manual_seed(6)
    hf = BloomForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "bloom_gen")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    tokens = np.random.default_rng(7).integers(0, 80, size=(1, 8))
    full = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))

    cache = model.init_cache(1, 12)
    logits, cache = model.prefill(params, jnp.asarray(tokens, jnp.int32),
                                  cache)
    np.testing.assert_allclose(np.asarray(logits), full, atol=2e-4,
                               rtol=2e-4)
    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    step_logits, cache = model.decode_step(params, cache, nxt,
                                           tokens.shape[1])
    tokens2 = np.concatenate([tokens, np.asarray(nxt)[:, None]], axis=1)
    full2 = np.asarray(model.apply(params, jnp.asarray(tokens2, jnp.int32)))
    np.testing.assert_allclose(np.asarray(step_logits), full2[:, -1],
                               atol=2e-4, rtol=2e-4)


def test_falcon_rw_alibi_parity(tmp_path_factory):
    """falcon-rw family: ALiBi + per-head interleaved QKV + sequential
    blocks with separate post-attention LN (review findings: alibi flag and
    non-MQA fused layout must not silently mis-convert)."""
    from transformers import FalconConfig, FalconForCausalLM

    cfg = FalconConfig(vocab_size=95, hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=4, bias=True, alibi=True,
                       parallel_attn=False, multi_query=False,
                       new_decoder_architecture=False,
                       max_position_embeddings=64)
    torch.manual_seed(8)
    hf = FalconForCausalLM(cfg).eval()
    with torch.no_grad():
        for p in hf.parameters():
            if p.ndim == 1:
                p.uniform_(-0.3, 0.3)
    path = _save(hf, tmp_path_factory, "falcon_rw")
    model = _parity(path, hf, 95)
    assert model.cfg.position == "alibi"
    assert not model.cfg.parallel_residual
    assert model.cfg.kv_heads == 4


def test_opt_untied_embeddings(tmp_path_factory):
    from transformers import OPTConfig, OPTForCausalLM

    cfg = OPTConfig(vocab_size=88, hidden_size=32, ffn_dim=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64, do_layer_norm_before=True,
                    word_embed_proj_dim=32, tie_word_embeddings=False)
    torch.manual_seed(9)
    hf = OPTForCausalLM(cfg).eval()
    with torch.no_grad():   # untie for real: distinct lm_head weights
        hf.lm_head.weight = torch.nn.Parameter(
            torch.randn_like(hf.lm_head.weight) * 0.1)
    path = _save(hf, tmp_path_factory, "opt_untied")
    model = _parity(path, hf, 88)
    assert not model.cfg.tie_embeddings


def test_mistral_sliding_window_parity(tmp_path_factory):
    """Mistral with seq > sliding_window: logits must match HF transformers
    (which masks keys beyond the window) — the r3 divergence where the
    window was dropped on import is now closed. Reference:
    inference/v2/model_implementations/mistral/model.py:202."""
    from transformers import MistralConfig, MistralForCausalLM

    cfg = MistralConfig(vocab_size=120, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=64,
                        sliding_window=8, tie_word_embeddings=False,
                        attn_implementation="eager")
    torch.manual_seed(0)
    hf = MistralForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "mistral_swa")
    # seq=20 > window=8: past-window keys must be masked
    model = _parity(path, hf, 120, seq=20)
    assert model.cfg.sliding_window == 8


def test_mistral_sliding_window_generate(tmp_path_factory):
    """v1 generate with a window shorter than the prompt matches HF greedy
    generation token-for-token."""
    from transformers import MistralConfig, MistralForCausalLM

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import from_pretrained

    cfg = MistralConfig(vocab_size=120, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=64,
                        sliding_window=8, tie_word_embeddings=False,
                        attn_implementation="eager")
    torch.manual_seed(1)
    hf = MistralForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "mistral_swa_gen")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngine(model, params=params)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 120, size=(2, 12))
    ours = np.asarray(engine.generate(jnp.asarray(prompt, jnp.int32),
                                      max_new_tokens=8))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(prompt), max_new_tokens=8,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_mistral_sliding_window_v2_serving(tmp_path_factory):
    """The v2 ragged engine (paged kernel + sliding window) serves a
    Mistral checkpoint with seq > window: last-token logits match the HF
    transformers forward at every decode step."""
    from transformers import MistralConfig, MistralForCausalLM

    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import from_pretrained

    cfg = MistralConfig(vocab_size=120, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=64,
                        sliding_window=8, tie_word_embeddings=False,
                        attn_implementation="eager")
    torch.manual_seed(2)
    hf = MistralForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "mistral_swa_v2")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngineV2(model, params=params,
                               config=RaggedInferenceEngineConfig(
                                   max_ragged_sequence_count=4,
                                   max_chunk_tokens=32, kv_blocks=64,
                                   kv_block_size=4))
    rng = np.random.default_rng(5)
    seq = rng.integers(0, 120, 20).tolist()      # 20 > window=8
    logits = engine.put([1], [seq])
    for step in range(5):
        ref = _hf_logits(hf, np.asarray([seq]))[0, -1]
        np.testing.assert_allclose(np.asarray(logits[0]), ref,
                                   atol=4e-4, rtol=4e-4,
                                   err_msg=f"decode step {step}")
        if step == 4:
            break                   # every issued put has been verified
        nxt = int(np.argmax(ref))
        seq.append(nxt)
        logits = engine.put([1], [[nxt]])


def test_gptj_forward_parity(tmp_path_factory):
    """GPT-J: shared single LayerNorm per block (ln_1 feeds both parallel
    branches), interleaved (rotate_every_two) partial rotary, bias-free
    attention with biased MLP, biased untied lm_head."""
    from transformers import GPTJConfig, GPTJForCausalLM

    cfg = GPTJConfig(vocab_size=160, n_embd=32, n_inner=64, n_layer=2,
                     n_head=4, n_positions=64, rotary_dim=4,
                     tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = GPTJForCausalLM(cfg).eval()
    with torch.no_grad():
        hf.lm_head.bias.uniform_(-0.5, 0.5)   # exercise the head bias
    path = _save(hf, tmp_path_factory, "gptj")
    model = _parity(path, hf, 160)
    assert model.cfg.shared_layernorm and model.cfg.rope_interleaved
    assert model.cfg.rot_dim == 4


def test_gptj_generate_matches_hf(tmp_path_factory):
    """Greedy cached generate (paged decode incl. interleaved rotary at
    per-sequence positions) matches HF token-for-token."""
    from transformers import GPTJConfig, GPTJForCausalLM

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import from_pretrained

    cfg = GPTJConfig(vocab_size=160, n_embd=32, n_inner=64, n_layer=2,
                     n_head=4, n_positions=64, rotary_dim=4,
                     tie_word_embeddings=False)
    torch.manual_seed(1)
    hf = GPTJForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "gptj_gen")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngine(model, params=params)
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, 160, size=(2, 10))
    ours = np.asarray(engine.generate(jnp.asarray(prompt, jnp.int32),
                                      max_new_tokens=8))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(prompt), max_new_tokens=8,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_phi_forward_parity(tmp_path_factory):
    """Phi (phi-1/phi-2): GPT-J-style single shared LayerNorm per block but
    with biases on every projection and rotate_half partial rotary."""
    from transformers import PhiConfig, PhiForCausalLM

    cfg = PhiConfig(vocab_size=140, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=64,
                    partial_rotary_factor=0.5, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = PhiForCausalLM(cfg).eval()
    with torch.no_grad():
        for p in hf.parameters():
            if p.ndim == 1:
                p.uniform_(-0.3, 0.3)
    path = _save(hf, tmp_path_factory, "phi")
    model = _parity(path, hf, 140)
    assert model.cfg.shared_layernorm and not model.cfg.rope_interleaved
    assert model.cfg.use_bias and model.cfg.lm_head_bias
    assert model.cfg.kv_heads == 2


def test_phi_generate_matches_hf(tmp_path_factory):
    from transformers import PhiConfig, PhiForCausalLM

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import from_pretrained

    cfg = PhiConfig(vocab_size=140, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64, partial_rotary_factor=0.5,
                    tie_word_embeddings=False)
    torch.manual_seed(3)
    hf = PhiForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "phi_gen")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngine(model, params=params)
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, 140, size=(2, 9))
    ours = np.asarray(engine.generate(jnp.asarray(prompt, jnp.int32),
                                      max_new_tokens=7))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(prompt), max_new_tokens=7,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_generate_eos_early_stop_matches_hf(tmp_path_factory):
    """eos_token_id: sequences pad (0) after emitting EOS — HF's early-stop
    semantics under fixed-shape scans (this exact Phi seed greedily emits
    token id 2 = eos mid-generation)."""
    from transformers import PhiConfig, PhiForCausalLM

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import from_pretrained

    cfg = PhiConfig(vocab_size=140, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64, partial_rotary_factor=0.5,
                    tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = PhiForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "phi_eos")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngine(model, params=params)
    prompt = np.random.default_rng(0).integers(0, 140, (1, 8))
    ours = np.asarray(engine.generate(jnp.asarray(prompt, jnp.int32),
                                      max_new_tokens=6, eos_token_id=2))
    theirs = hf.generate(torch.tensor(prompt), max_new_tokens=6,
                         do_sample=False, eos_token_id=2,
                         pad_token_id=0).numpy()
    # HF truncates at the longest finished length; compare the overlap and
    # require our remainder to be pad
    L = theirs.shape[1]
    np.testing.assert_array_equal(ours[:, :L], theirs)
    assert (ours[:, L:] == 0).all()
    assert 2 in ours[0].tolist(), "the eos token itself must be emitted"


@pytest.mark.parametrize("parallel", [False, True])
def test_stablelm_forward_parity(tmp_path_factory, parallel):
    """StableLM: llama-shaped SwiGLU blocks with biased LayerNorm and
    partial rotary; use_parallel_residual drops post_attention_layernorm
    entirely (the GPT-J shared-LN pattern)."""
    from transformers import StableLmConfig, StableLmForCausalLM

    cfg = StableLmConfig(vocab_size=130, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=64,
                         partial_rotary_factor=0.25, use_qkv_bias=True,
                         use_parallel_residual=parallel,
                         tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = StableLmForCausalLM(cfg).eval()
    with torch.no_grad():
        for p in hf.parameters():
            if p.ndim == 1:
                p.uniform_(-0.3, 0.3)
    path = _save(hf, tmp_path_factory, f"stablelm{int(parallel)}")
    model = _parity(path, hf, 130)
    assert model.cfg.qkv_bias and model.cfg.rope_pct == 0.25
    assert model.cfg.shared_layernorm == parallel


def test_stablelm_generate_matches_hf(tmp_path_factory):
    from transformers import StableLmConfig, StableLmForCausalLM

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import from_pretrained

    cfg = StableLmConfig(vocab_size=130, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=64,
                         partial_rotary_factor=0.25,
                         tie_word_embeddings=False)
    torch.manual_seed(4)
    hf = StableLmForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "stablelm_gen")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngine(model, params=params)
    prompt = np.random.default_rng(13).integers(0, 130, size=(2, 9))
    ours = np.asarray(engine.generate(jnp.asarray(prompt, jnp.int32),
                                      max_new_tokens=7))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(prompt), max_new_tokens=7,
                             do_sample=False, eos_token_id=None).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_qwen2_mixed_window_schedule_parity(tmp_path_factory):
    """Qwen2 with 0 < max_window_layers < num_layers (HF: the first
    max_window_layers layers use full attention, the rest SWA) imports as
    a per-layer window tuple and matches HF logits at seq > window — the
    r4 rejection in convert.py is gone. Reference window plumb-through:
    inference/v2/model_implementations/mistral/model.py:202."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    cfg = Qwen2Config(vocab_size=120, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      use_sliding_window=True, sliding_window=8,
                      max_window_layers=2, tie_word_embeddings=False,
                      attn_implementation="eager")
    torch.manual_seed(6)
    hf = Qwen2ForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "qwen2_mixed_swa")
    # seq=20 > window=8: the two SWA layers must mask past-window keys
    # while the two full layers must not
    model = _parity(path, hf, 120, seq=20)
    assert model.cfg.layer_windows() == (0, 0, 8, 8)
    assert model.cfg.window_segments() == ((0, 2, 0), (2, 2, 8))


def test_qwen2_mixed_window_generate(tmp_path_factory):
    """v1 generate through the mixed full/SWA layer schedule matches HF
    greedy generation token-for-token (decode runs the segmented layer
    scan with a per-segment window mask)."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import from_pretrained

    cfg = Qwen2Config(vocab_size=120, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      use_sliding_window=True, sliding_window=8,
                      max_window_layers=2, tie_word_embeddings=False,
                      attn_implementation="eager")
    torch.manual_seed(8)
    hf = Qwen2ForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "qwen2_mixed_swa_gen")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngine(model, params=params)
    prompt = np.random.default_rng(21).integers(0, 120, size=(2, 12))
    ours = np.asarray(engine.generate(jnp.asarray(prompt, jnp.int32),
                                      max_new_tokens=8))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(prompt), max_new_tokens=8,
                             do_sample=False, eos_token_id=None).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_qwen2_mixed_window_v2_serving(tmp_path_factory):
    """The v2 ragged engine serves the mixed full/SWA Qwen2 schedule (the
    segmented layer scan passes each run's window to the paged kernel):
    last-token logits match the HF forward at every decode step."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import from_pretrained

    cfg = Qwen2Config(vocab_size=120, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      use_sliding_window=True, sliding_window=8,
                      max_window_layers=2, tie_word_embeddings=False,
                      attn_implementation="eager")
    torch.manual_seed(11)
    hf = Qwen2ForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "qwen2_mixed_swa_v2")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngineV2(model, params=params,
                               config=RaggedInferenceEngineConfig(
                                   max_ragged_sequence_count=4,
                                   max_chunk_tokens=32, kv_blocks=64,
                                   kv_block_size=4))
    rng = np.random.default_rng(7)
    seq = rng.integers(0, 120, 20).tolist()      # 20 > window=8
    logits = engine.put([1], [seq])
    for step in range(4):
        ref = _hf_logits(hf, np.asarray([seq]))[0, -1]
        np.testing.assert_allclose(np.asarray(logits[0]), ref,
                                   atol=4e-4, rtol=4e-4,
                                   err_msg=f"decode step {step}")
        if step == 3:
            break
        nxt = int(np.argmax(ref))
        seq.append(nxt)
        logits = engine.put([1], [[nxt]])


def test_gptneo_forward_parity(tmp_path_factory):
    """GPT-Neo (reference module_inject/containers/gptneo.py): alternating
    global/LOCAL attention — the local layers are causal sliding windows
    riding the per-layer window tuple — plus UNSCALED attention
    (attn_scale=1.0) and out-proj-only attention bias. Logits pinned vs
    HF at seq > window_size."""
    from transformers import GPTNeoConfig, GPTNeoForCausalLM

    cfg = GPTNeoConfig(vocab_size=130, hidden_size=32, num_layers=4,
                       attention_types=[[["global", "local"], 2]],
                       num_heads=4, intermediate_size=64,
                       max_position_embeddings=64, window_size=8,
                       embed_dropout=0.0, attention_dropout=0.0,
                       resid_dropout=0.0)
    torch.manual_seed(12)
    hf = GPTNeoForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "gptneo")
    # seq=20 > window=8: local layers must mask past-window keys
    model = _parity(path, hf, 130, seq=20)
    assert model.cfg.attn_scale == 1.0
    assert model.cfg.layer_windows() == (0, 8, 0, 8)
    assert model.cfg.o_bias and not model.cfg.use_bias


def test_gptneo_generate_matches_hf(tmp_path_factory):
    from transformers import GPTNeoConfig, GPTNeoForCausalLM

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models import from_pretrained

    cfg = GPTNeoConfig(vocab_size=130, hidden_size=32, num_layers=4,
                       attention_types=[[["global", "local"], 2]],
                       num_heads=4, intermediate_size=64,
                       max_position_embeddings=64, window_size=8,
                       embed_dropout=0.0, attention_dropout=0.0,
                       resid_dropout=0.0)
    torch.manual_seed(13)
    hf = GPTNeoForCausalLM(cfg).eval()
    path = _save(hf, tmp_path_factory, "gptneo_gen")
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    engine = InferenceEngine(model, params=params)
    prompt = np.random.default_rng(31).integers(0, 130, size=(2, 12))
    ours = np.asarray(engine.generate(jnp.asarray(prompt, jnp.int32),
                                      max_new_tokens=8))
    with torch.no_grad():
        theirs = hf.generate(torch.tensor(prompt), max_new_tokens=8,
                             do_sample=False, eos_token_id=None).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_llama_attention_bias_parity(tmp_path_factory):
    """Llama with attention_bias=True (the InternLM layout — reference
    module_inject/containers/internlm.py: Llama + biased q/k/v/o): biases
    load and logits match HF."""
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=120, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      attention_bias=True, tie_word_embeddings=False)
    torch.manual_seed(14)
    hf = LlamaForCausalLM(cfg).eval()
    with torch.no_grad():   # nonzero biases so the path is exercised
        for layer in hf.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj, layer.self_attn.o_proj):
                proj.bias.uniform_(-0.3, 0.3)
    path = _save(hf, tmp_path_factory, "llama_bias")
    model = _parity(path, hf, 120)
    assert model.cfg.use_bias and model.cfg.mlp_bias is False
