"""Compression suite tests (reference tests/unit/compression/test_compression.py):
quantizer/pruner numerics, STE gradients, config-driven transform matching,
QAT end-to-end through the engine, and redundancy_clean export."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.compression import (
    CompressionTransform, channel_prune, head_prune, init_compression,
    quantize_activation, quantize_weight, redundancy_clean, row_prune,
    sparse_prune, student_initialization, sym_quantize, topk_binarize)
from deepspeed_tpu.models import build_model


# ------------------------------------------------------------- primitives
def test_sym_quantize_levels_and_ste():
    x = jnp.linspace(-1.0, 1.0, 64)
    q = sym_quantize(x, 4, 1)
    assert len(np.unique(np.asarray(q))) <= 2 ** 4
    np.testing.assert_allclose(np.asarray(q), np.asarray(x), atol=0.15)
    # STE: gradient of sum(quantize(x)) is all-ones
    g = jax.grad(lambda v: sym_quantize(v, 4, 1).sum())(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)


def test_quantize_groups_independent_scales():
    x = jnp.concatenate([jnp.ones(8) * 0.01, jnp.ones(8) * 100.0])
    q1 = quantize_weight(x, 8, num_groups=1)
    q2 = quantize_weight(x, 8, num_groups=2)
    # one shared scale crushes the small half; per-group scales keep it
    assert np.abs(np.asarray(q2[:8]) - 0.01).max() < 1e-4
    assert np.abs(np.asarray(q1[:8]) - 0.01).max() > 1e-4


def test_sparse_prune_ratio():
    w = jnp.asarray(np.random.default_rng(0).normal(size=(32, 32)))
    out = np.asarray(sparse_prune(w, ratio=0.75))
    kept = (out != 0).mean()
    assert 0.2 <= kept <= 0.3
    # survivors are the largest magnitudes
    assert np.abs(out).max() == np.abs(np.asarray(w)).max()


def test_row_and_channel_prune_structured():
    w = jnp.asarray(np.random.default_rng(1).normal(size=(4, 16, 8)))
    r = np.asarray(row_prune(w, ratio=0.5))
    zero_rows = (np.abs(r).sum(-1) == 0).sum(axis=-1)
    np.testing.assert_array_equal(zero_rows, 8)      # half the 16 rows, per layer
    c = np.asarray(channel_prune(w, ratio=0.25))
    zero_ch = (np.abs(c).sum(-2) == 0).sum(axis=-1)
    np.testing.assert_array_equal(zero_ch, 2)        # quarter of 8 channels


@pytest.mark.parametrize("axis", ["in", "out"])
def test_head_prune(axis):
    w = jnp.asarray(np.random.default_rng(2).normal(size=(16, 16)))
    out = np.asarray(head_prune(w, ratio=0.5, num_heads=4, axis=axis))
    g = out.reshape(4, 4, 16) if axis == "in" else \
        out.transpose(1, 0).reshape(4, 4, 16)
    zeroed = sum(1 for h in range(4) if np.abs(g[h]).sum() == 0)
    assert zeroed == 2


def test_quantize_activation():
    x = jnp.asarray(np.random.default_rng(3).normal(size=(128,)))
    q8 = quantize_activation(x, 8)
    assert np.abs(np.asarray(q8) - np.asarray(x)).max() < 0.05
    g = jax.grad(lambda v: quantize_activation(v, 8).sum())(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)


# ---------------------------------------------------------------- transform
def qat_config(offset=0):
    return {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": offset},
            "different_groups": {
                "wq1": {"params": {"target_bits": 6, "quantize_groups": 1},
                        "modules": ["layers.*"]}}}}}


def test_transform_matches_modules_and_offset():
    w = np.random.default_rng(0).normal(size=(8, 8)).astype(np.float32)
    params = {"layers": {"w": jnp.asarray(w)},
              "embed": {"wte": jnp.asarray(w)}}
    t = CompressionTransform(qat_config(offset=5))
    out_before = t(params, global_step=0)
    out_after = t(params, global_step=10)
    # before offset: untouched; after: layers quantized, embed untouched
    np.testing.assert_allclose(np.asarray(out_before["layers"]["w"]), w)
    assert not np.allclose(np.asarray(out_after["layers"]["w"]), w)
    np.testing.assert_allclose(np.asarray(out_after["embed"]["wte"]), w)


def test_redundancy_clean():
    params = {"layers": {"w": jnp.asarray(
        np.random.default_rng(0).normal(size=(16, 16)))}}
    cleaned = redundancy_clean(params, {"compression_training": {
        "sparse_pruning": {
            "shared_parameters": {"enabled": True},
            "different_groups": {
                "sp1": {"params": {"dense_ratio": 0.5},
                        "modules": ["*"]}}}}})
    assert (np.asarray(cleaned["layers"]["w"]) == 0).mean() >= 0.45


def test_student_initialization():
    params = {"layers": {"w": jnp.arange(6, dtype=jnp.float32)[:, None]
                         * jnp.ones((6, 3))},
              "embed": {"wte": jnp.ones((4, 3))}}
    student = student_initialization(params, keep_layers=[0, 3, 5])
    assert student["layers"]["w"].shape == (3, 3)
    np.testing.assert_allclose(np.asarray(student["layers"]["w"][:, 0]),
                               [0.0, 3.0, 5.0])
    assert student["embed"]["wte"].shape == (4, 3)


# ------------------------------------------------------------ engine (QAT)
def test_engine_qat_trains(devices8):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": -1, "fsdp": 2},
        "steps_per_print": 10**9,
    }
    cfg.update(qat_config(offset=0))
    engine, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                               config=cfg)
    assert engine._compression
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, size=(32, 33),
                                       dtype=np.int64)}
    losses = [float(engine.train_batch(itertools.repeat(batch)))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # exported (cleaned) layer weights quantize to <= 2^6 distinct levels
    cleaned = engine._compression.clean(engine.state.params)
    w = np.asarray(jax.tree.leaves(cleaned["layers"])[0])
    assert len(np.unique(w[0] if w.ndim == 3 else w)) <= 2 ** 6


def test_init_compression_engine_api(devices8):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1, "fsdp": 1},
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                               config=cfg)
    assert engine._compression is None
    init_compression(engine, qat_config(offset=0))
    assert engine._compression
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 256, size=(16, 33),
                                       dtype=np.int64)}
    loss = engine.train_batch(itertools.repeat(batch))
    assert np.isfinite(float(loss))
