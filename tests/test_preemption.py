"""Reservation-aware admission + preemptive KV spill (ISSUE 11;
docs/SERVING.md "Admission and preemption").

Invariants under test: the chunked-admission deadlock (kv_blocks=14,
8 seqs, 76-token prompts) reproduces on the old path and is structurally
impossible under reservation admission; the ledger credits prefix-cache
hits and releases on cancel/finish; victim selection orders by urgency
class, then blocks, then progress; a preempted sequence's KV round-trips
the spill store byte-for-byte (fp32 AND int8 + scale planes) and its
greedy stream is byte-identical to an uncontended run; preemption
composes with cancel and the disaggregated handoff; the
``max_preemptions_per_seq`` starvation cap holds; and the all-default
``admission`` block is byte-for-byte the historical scheduler."""

import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.scheduler import (ContinuousBatchingScheduler,
                                                  Request)
from deepspeed_tpu.inference.v2.testing import assert_greedy_parity
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving.config import AdmissionConfig

VOCAB = 128
BS = 8          # kv block size used throughout


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=2,
                            max_seq_len=128, norm="rmsnorm",
                            activation="silu", position="rope")
    model = CausalLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def make_engine(model, params, kv_blocks=14, max_seqs=8, prefix=False,
                quant=False, qdtype="int8", tier=False, reservation=False,
                preempt=False, factor=1.0, policy="lowest_class",
                max_preempts=2):
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=256, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=kv_blocks, kv_block_size=BS,
        max_tracked_sequences=64, enable_prefix_cache=prefix,
        kv_quant_enabled=quant, kv_quant_dtype=qdtype,
        admission_reservation=reservation,
        admission_oversubscription_factor=factor,
        admission_preemption_enabled=preempt,
        admission_victim_policy=policy,
        admission_max_preemptions_per_seq=max_preempts)
    eng = InferenceEngineV2(model, params=params, config=vcfg)
    if tier:
        eng.configure_kv_tier(True, host_bytes=64 << 20)
    return eng


def rand_prompt(rng, n):
    return rng.integers(0, VOCAB, size=n).tolist()


def reference_streams(model, params, jobs, uid_base=90_000,
                      quant=False, qdtype="int8"):
    """Uncontended sequential greedy streams (big pool, old admission)
    — the parity baseline, at the SAME KV representation as the engine
    under test (spill/resume is lossless relative to its own pools).
    ``jobs`` = [(prompt, max_new), ...]."""
    eng = make_engine(model, params, kv_blocks=256, max_seqs=8,
                      quant=quant, qdtype=qdtype)
    sched = ContinuousBatchingScheduler(eng)
    out = []
    for i, (p, mn) in enumerate(jobs):
        sched.submit(uid_base + i, p, max_new_tokens=mn)
        sched.run_to_completion()
        out.append(sched.finished[uid_base + i].generated)
    return out


# -------------------------------------------------- deadlock regression
def test_chunked_admission_deadlock_regression(model_and_params):
    """The ROADMAP-confirmed production killer, on the exact regime
    that surfaced it (kv_blocks=14, 8 sequences, 76-token prompts):
    chunk-by-chunk admission part-prefills every sequence until the
    pool is exhausted with none able to finish — bounded steps, zero
    completions, blocks stranded. Under reservation admission the same
    traffic completes, with greedy streams byte-identical to an
    uncontended run."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    prompts = [rand_prompt(rng, 76) for _ in range(8)]

    # old path: wedged forever (bounded-time assert, not a hang)
    eng = make_engine(model, params, reservation=False)
    sched = ContinuousBatchingScheduler(eng)
    for i, p in enumerate(prompts):
        sched.submit(100 + i, p, max_new_tokens=4)
    for _ in range(40):
        sched.step()
    assert not sched.finished, "expected the historical wedge"
    assert sched.has_work
    assert eng.free_blocks < 4        # the pool is stranded, not idle

    # reservation admission: same pool, same traffic, all complete
    eng2 = make_engine(model, params, reservation=True)
    sched2 = ContinuousBatchingScheduler(eng2)
    for i, p in enumerate(prompts):
        sched2.submit(200 + i, p, max_new_tokens=4)
    fin = sched2.run_to_completion(max_steps=2000)
    assert len(fin) == 8
    assert eng2.free_blocks == 14     # everything reclaimed
    ref = reference_streams(model, params, [(p, 4) for p in prompts])
    assert_greedy_parity(ref, [fin[200 + i].generated for i in range(8)],
                         "reservation admission")


# ------------------------------------------------------ reservation ledger
def test_ledger_reserve_release_and_headroom(model_and_params):
    model, params = model_and_params
    eng = make_engine(model, params, kv_blocks=14)
    sm = eng.state_manager
    assert eng.reservation_headroom() == 14
    assert eng.try_reserve(1, 10)
    assert eng.reservation_headroom() == 4
    assert not eng.try_reserve(2, 5)          # shortfall: wait
    assert eng.try_reserve(2, 4)
    assert eng.reservation_headroom() == 0
    # re-reserving the same uid replaces, never double-counts
    assert eng.try_reserve(2, 4)
    assert sm.reserved_sequences == 2
    # flush releases the reservation with the state
    eng.flush(1)
    assert eng.reservation_headroom() == 10
    eng.release_reservation(2)
    assert eng.reservation_headroom() == 14
    # force_reserve records over-commitments (the import path)
    eng.force_reserve(3, 20)
    assert eng.reservation_headroom() == -6


def test_ledger_unfilled_tracks_allocation(model_and_params):
    """A reserved sequence's claim shrinks as it allocates: headroom is
    available minus UNFILLED needs, so admitted work never double-counts
    blocks it already holds."""
    model, params = model_and_params
    rng = np.random.default_rng(1)
    eng = make_engine(model, params, kv_blocks=14)
    assert eng.try_reserve(700, 6)
    assert eng.reservation_headroom() == 8
    eng.put([700], [rand_prompt(rng, 24)])    # 3 blocks allocated
    # available dropped by 3, but so did the unfilled claim
    assert eng.reservation_headroom() == 8


def test_prefix_hit_credits_reservation(model_and_params):
    """Blocks served from the prefix cache count toward the reservation:
    a warm-cache request reserves only its unfilled tail, so cache hits
    buy admission slots, not just prefill time."""
    model, params = model_and_params
    rng = np.random.default_rng(2)
    sys_prompt = rand_prompt(rng, 32)         # 4 full blocks
    eng = make_engine(model, params, kv_blocks=14, prefix=True,
                      reservation=True)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(300, sys_prompt + rand_prompt(rng, 6), max_new_tokens=2)
    sched.run_to_completion()
    # 4 cached blocks + a second request whose prefix matches them:
    # its 40+2-token need (6 blocks) reserves only the uncached tail
    eng.try_reserve(998, eng.reservation_headroom())   # squeeze the pool
    matched = eng.match_prefix(301, sys_prompt + rand_prompt(rng, 6))
    assert matched == 32
    # unfilled = 6 total - 4 matched = 2; grant exactly that much room
    eng.release_reservation(998)
    eng.try_reserve(998, eng.reservation_headroom() - 2)
    assert eng.try_reserve(301, 6)


def test_reservation_released_on_cancel(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(3)
    eng = make_engine(model, params, reservation=True)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(310, rand_prompt(rng, 60), max_new_tokens=20)
    for _ in range(2):
        sched.step()
    assert eng.state_manager.reserved_sequences == 1
    assert sched.cancel(310)
    assert eng.state_manager.reserved_sequences == 0
    assert eng.free_blocks == 14


# ------------------------------------------------------- victim selection
def _req(uid, shed_rank, fed=0, gen=0):
    r = Request(uid, [0] * 10, 8, shed_rank=shed_rank)
    r.prompt_fed = fed
    r.generated = [0] * gen
    return r


def test_victim_policy_ordering(model_and_params):
    model, params = model_and_params
    eng = make_engine(model, params, preempt=True)
    sched = ContinuousBatchingScheduler(eng)
    a = _req(1, shed_rank=0, fed=10, gen=4)   # interactive, 4 blocks
    b = _req(2, shed_rank=1, fed=10, gen=1)   # batch, few blocks
    c = _req(3, shed_rank=1, fed=10, gen=6)   # batch, most blocks
    # lowest_class: batch before interactive; most blocks wins the tie;
    # least progress breaks block ties
    order = sorted([(a, 4), (b, 2), (c, 6)],
                   key=lambda t: sched._victim_order(*t), reverse=True)
    assert [r.uid for r, _ in order] == [3, 2, 1]
    sched.victim_policy = "most_blocks"
    order = sorted([(a, 4), (b, 2), (c, 6)],
                   key=lambda t: sched._victim_order(*t), reverse=True)
    assert [r.uid for r, _ in order] == [3, 1, 2]
    sched.victim_policy = "least_progress"
    order = sorted([(a, 4), (b, 2), (c, 6)],
                   key=lambda t: sched._victim_order(*t), reverse=True)
    assert [r.uid for r, _ in order] == [2, 1, 3]


def test_admission_preempts_only_lower_urgency(model_and_params):
    """Admission-driven preemption requires a STRICTLY lower-urgency
    victim — same-class overload waits (preempting peer work to admit
    identical work is churn), lower-class work is spilled."""
    model, params = model_and_params
    rng = np.random.default_rng(4)
    eng = make_engine(model, params, reservation=True, preempt=True,
                      factor=3.0)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(400, rand_prompt(rng, 60), max_new_tokens=20, shed_rank=1)
    for _ in range(4):
        sched.step()
    assert 400 in sched.running
    # same class: no preemption, the newcomer waits
    sched.submit(401, rand_prompt(rng, 60), max_new_tokens=4, shed_rank=1)
    sched.step()
    assert sched.preempt_stats()["preempted"] == 0
    assert sched.pending and sched.pending[0].uid == 401
    assert sched.reserve_shortfall_blocks() > 0
    # higher urgency: the batch resident is spilled
    sched.submit(402, rand_prompt(rng, 60), max_new_tokens=4, shed_rank=0)
    sched.step()
    assert sched.preempt_stats()["preempted"] == 1
    assert 400 in sched.preempted
    fin = sched.run_to_completion(max_steps=2000)
    assert sorted(fin) == [400, 401, 402]
    assert sched.preempt_stats()["resumed"] == 1


# -------------------------------------------------- spill/resume round-trip
@pytest.mark.parametrize("quant", [False, True],
                         ids=["fp32", "int8+scales"])
@pytest.mark.parametrize("qdtype", ["int8", "fp8_e4m3"])
def test_preempt_spill_resume_byte_roundtrip(model_and_params, quant,
                                             qdtype):
    """A preempted sequence's KV round-trips the spill store exactly —
    pool slabs (and the int8/fp8 scale planes under kv_quant) byte-equal
    after resume, and the resumed greedy stream is byte-identical to an
    uncontended run at the same representation (the spilled logits are
    the decode state). The ISSUE 13 dtype axis rides this same test."""
    if not quant and qdtype != "int8":
        pytest.skip("dtype axis only exists under kv_quant")
    model, params = model_and_params
    rng = np.random.default_rng(5)
    prompts = [rand_prompt(rng, 60), rand_prompt(rng, 60)]
    eng = make_engine(model, params, prefix=True, tier=True, quant=quant,
                      qdtype=qdtype, reservation=True, preempt=True,
                      factor=3.0)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(500, prompts[0], max_new_tokens=16, shed_rank=1)
    for _ in range(4):
        sched.step()
    seq = eng.state_manager.get_sequence(500)
    ids_before = list(seq.kv_blocks)
    before = {name: np.asarray(np.take(np.asarray(pool), ids_before, axis=1))
              for name, pool in eng.state_manager.kv_cache.items()}
    seen_before = seq.seen_tokens
    sched.submit(501, prompts[1], max_new_tokens=4, shed_rank=0)
    sched.step()
    assert 500 in sched.preempted
    assert eng.state_manager.get_sequence(500) is None   # blocks freed
    # drive until 500 resumes, then compare its re-imported slabs
    for _ in range(2000):
        sched.step()
        if 500 in sched.running:
            break
    assert 500 in sched.running, "preempted sequence never resumed"
    seq2 = eng.state_manager.get_sequence(500)
    # the step that resumed the sequence may also have packed its next
    # decode row — the import itself restored exactly seen_before tokens
    assert seq2.seen_tokens in (seen_before, seen_before + 1)
    after = {name: np.asarray(np.take(np.asarray(pool),
                                      list(seq2.kv_blocks), axis=1))
             for name, pool in eng.state_manager.kv_cache.items()}
    assert set(after) == set(before)
    if quant:
        assert {"k_scale", "v_scale"} <= set(after)
    # compare the blocks that were FULL at capture time — the partial
    # tail block legitimately gained the post-resume decode token
    n_full = seen_before // BS
    assert n_full >= 7
    for name in before:
        np.testing.assert_array_equal(before[name][:, :n_full],
                                      after[name][:, :n_full],
                                      err_msg=f"slab {name} diverged "
                                              "across spill/resume")
    fin = sched.run_to_completion(max_steps=2000)
    ref = reference_streams(model, params,
                            [(prompts[0], 16), (prompts[1], 4)],
                            quant=quant, qdtype=qdtype)
    assert_greedy_parity(ref, [fin[500].generated, fin[501].generated],
                         f"preempt round-trip (quant={quant}/{qdtype})")


def test_resume_falls_back_to_reprefill_when_payload_dropped(
        model_and_params):
    """A spilled payload the tier lost (byte bounds, corruption) cannot
    crash the resume: the sequence re-prefills prompt + delivered tokens
    and the greedy stream stays byte-identical (failover semantics)."""
    model, params = model_and_params
    rng = np.random.default_rng(6)
    prompts = [rand_prompt(rng, 60), rand_prompt(rng, 60)]
    eng = make_engine(model, params, reservation=True, preempt=True,
                      factor=3.0)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(510, prompts[0], max_new_tokens=16, shed_rank=1)
    for _ in range(4):
        sched.step()
    sched.submit(511, prompts[1], max_new_tokens=4, shed_rank=0)
    sched.step()
    assert 510 in sched.preempted
    # simulate the tier dropping the payload
    eng.preempt_discard(510)
    fin = sched.run_to_completion(max_steps=2000)
    ref = reference_streams(model, params,
                            [(prompts[0], 16), (prompts[1], 4)])
    assert_greedy_parity(ref, [fin[510].generated, fin[511].generated],
                         "dropped-payload re-prefill")


# ------------------------------------------------------------------- races
def test_preempt_vs_cancel_race(model_and_params):
    """Cancelling a PARKED sequence settles terminally: the spilled
    payload is discarded, on_finish fires with "cancelled", and the
    sequence never resurrects on resume."""
    model, params = model_and_params
    rng = np.random.default_rng(7)
    eng = make_engine(model, params, prefix=True, tier=True,
                      reservation=True, preempt=True, factor=3.0)
    sched = ContinuousBatchingScheduler(eng)
    finished = []
    sched.submit(520, rand_prompt(rng, 60), max_new_tokens=16, shed_rank=1,
                 on_finish=lambda r, why: finished.append((r.uid, why)))
    for _ in range(4):
        sched.step()
    sched.submit(521, rand_prompt(rng, 60), max_new_tokens=4, shed_rank=0)
    sched.step()
    assert 520 in sched.preempted
    assert sched.cancel(520)
    assert (520, "cancelled") in finished
    assert 520 not in sched.preempted
    assert eng.state_manager.preempted_parked == 0
    fin = sched.run_to_completion(max_steps=2000)
    assert 520 not in sched.running and 521 in fin
    assert sched.preempt_stats()["resumed"] == 0


def test_preempt_composes_with_disagg_handoff(model_and_params):
    """Preemption on a role-split fleet: staged KV imports land on the
    decode replica (force-reserved), batch decodes get preempted for
    interactive bursts, and every stream still matches the uncontended
    reference — handoff, reservation, and preemption compose."""
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend

    model, params = model_and_params
    rng = np.random.default_rng(8)
    batch_p = [rand_prompt(rng, 40) for _ in range(3)]
    inter_p = [rand_prompt(rng, 40) for _ in range(3)]

    def factory(i):
        return make_engine(model, params, kv_blocks=20, max_seqs=8)

    cfg = ServingConfig(
        max_queue_depth=64,
        disaggregation={"enabled": True, "roles": ["prefill", "decode"]},
        admission={"reservation": True, "oversubscription_factor": 3.0,
                   "preemption": {"enabled": True}})
    fe = ServingFrontend.from_engine_factory(
        factory, cfg.model_copy(update={"num_replicas": 2}))
    try:
        hb = [fe.submit(p, max_new_tokens=16, request_class="batch")
              for p in batch_p]
        time.sleep(0.5)
        hi = [fe.submit(p, max_new_tokens=4, request_class="interactive")
              for p in inter_p]
        assert fe.wait_all(hb + hi, timeout=240)
        got = [[ev.token for ev in h.drain()] for h in hb + hi]
    finally:
        fe.shutdown(drain=False, timeout=5)
    ref = reference_streams(model, params,
                            [(p, 16) for p in batch_p]
                            + [(p, 4) for p in inter_p])
    assert_greedy_parity(ref, got, "disagg + preemption")


# --------------------------------------------------------- starvation cap
def test_max_preemptions_per_seq_starvation_cap(model_and_params):
    """A sequence spilled ``max_preemptions_per_seq`` times becomes
    immune: later higher-urgency arrivals wait instead of starving it,
    and it still completes."""
    model, params = model_and_params
    rng = np.random.default_rng(9)
    eng = make_engine(model, params, reservation=True, preempt=True,
                      factor=4.0, max_preempts=1)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(530, rand_prompt(rng, 60), max_new_tokens=30, shed_rank=1)
    for _ in range(4):
        sched.step()
    sched.submit(531, rand_prompt(rng, 60), max_new_tokens=2, shed_rank=0)
    sched.step()
    assert sched.preempt_stats()["preempted"] == 1        # spill #1
    # run until 530 is resident again, then hit it with another burst
    for _ in range(2000):
        sched.step()
        if 530 in sched.running and 531 in sched.finished:
            break
    sched.submit(532, rand_prompt(rng, 60), max_new_tokens=2, shed_rank=0)
    fin = sched.run_to_completion(max_steps=3000)
    assert sched.preempt_stats()["preempted"] == 1        # cap held
    assert sorted(fin) == [530, 531, 532]                 # nobody starved


# ------------------------------------------------------- disabled parity
def test_disabled_admission_byte_parity(model_and_params):
    """``admission`` all-default through the serving config surface is
    byte-for-byte a config that never heard of the block."""
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend

    model, params = model_and_params
    rng = np.random.default_rng(10)
    reqs = [rand_prompt(rng, 24) for _ in range(6)]

    def run(extra):
        fe = ServingFrontend(
            [make_engine(model, params, kv_blocks=64, max_seqs=4)],
            ServingConfig(max_queue_depth=64, **extra))
        try:
            hs = [fe.submit(p, max_new_tokens=4) for p in reqs]
            assert fe.wait_all(hs, timeout=240)
            return [[ev.token for ev in h.drain()] for h in hs]
        finally:
            fe.shutdown(drain=False, timeout=5)

    absent = run({})
    disabled = run({"admission": {"reservation": False,
                                  "preemption": {"enabled": False}}})
    assert disabled == absent


def test_scheduler_defaults_keep_old_admission(model_and_params):
    """A default-config scheduler still takes the historical
    chunk-by-chunk path: no ledger entries, no preemption state."""
    model, params = model_and_params
    rng = np.random.default_rng(11)
    eng = make_engine(model, params, kv_blocks=64)
    sched = ContinuousBatchingScheduler(eng)
    assert not sched.reservation and not sched.preempt_enabled
    sched.submit(540, rand_prompt(rng, 24), max_new_tokens=4)
    sched.run_to_completion()
    assert eng.state_manager.reserved_sequences == 0
    assert sched.preempt_stats() == {"preempted": 0, "resumed": 0}


# --------------------------------------------------------- serving surface
def test_queue_counts_preempt_pressure_sheds():
    """Overload sheds during a preemption-pressure window count
    ``requests_shed_preempt_pressure``; brownout sheds never do."""
    from deepspeed_tpu.serving import Rejected
    from deepspeed_tpu.serving.metrics import serving_metrics
    from deepspeed_tpu.serving.queue import AdmissionQueue
    from deepspeed_tpu.serving.request import ServingRequest

    def req(prio=1):
        return ServingRequest([1, 2], 4, prio, None, None)

    m = serving_metrics()
    q = AdmissionQueue(1, m)
    q.offer(req())
    with pytest.raises(Rejected):
        q.offer(req())                       # no pressure: plain overload
    assert m.counter("requests_shed_preempt_pressure").value == 0
    q.set_preempt_pressure(True)
    with pytest.raises(Rejected):
        q.offer(req())
    assert m.counter("requests_shed_preempt_pressure").value == 1
    q.set_preempt_pressure(False)
    with pytest.raises(Rejected):
        q.offer(req())
    assert m.counter("requests_shed_preempt_pressure").value == 1


def test_frontend_publishes_preempt_metrics_and_journal(model_and_params):
    """The serving surface of a preempting fleet: sequences_preempted /
    sequences_resumed counters, spill/resume histograms, the
    ``sequence_preempted`` journal event (schema-valid), and the
    health-report occupancy/counter integration."""
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend
    from deepspeed_tpu.telemetry import validate_events

    model, params = model_and_params
    rng = np.random.default_rng(12)

    def factory(i):
        return make_engine(model, params, kv_blocks=14, max_seqs=8)

    cfg = ServingConfig(
        max_queue_depth=64, prefix_cache={"enabled": True},
        kv_tier={"enabled": True},
        admission={"reservation": True, "oversubscription_factor": 3.0,
                   "preemption": {"enabled": True}})
    fe = ServingFrontend.from_engine_factory(factory, cfg)
    try:
        hb = [fe.submit(rand_prompt(rng, 60), max_new_tokens=24,
                        request_class="batch") for _ in range(4)]
        time.sleep(0.6)
        hi = [fe.submit(rand_prompt(rng, 60), max_new_tokens=4,
                        request_class="interactive") for _ in range(8)]
        assert fe.wait_all(hb + hi, timeout=240)
        snap = fe.metrics_snapshot()
        assert snap["sequences_preempted"] > 0
        assert snap["sequences_resumed"] > 0
        assert snap["preempt_spill_s"]["count"] > 0
        assert snap["preempt_resume_s"]["count"] > 0
        evs = fe.journal.events(kinds=("sequence_preempted",))
        assert evs and evs[0]["detail"]["blocks"] > 0
        assert not validate_events(fe.journal.events())
        rep = fe.health_report()
        assert rep["counters"]["sequences_preempted"] > 0
        assert "preempted_resident_blocks" in rep["occupancy"]
        assert "queue_wait_blocks" in rep["occupancy"]
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_freeable_blocks_excludes_shared_prefix(model_and_params):
    """Victim selection counts only blocks a flush would actually free:
    prefix blocks another live sequence shares return nothing, so a
    mostly-shared victim must not be spilled for headroom that never
    materializes."""
    model, params = model_and_params
    rng = np.random.default_rng(13)
    sys_prompt = rand_prompt(rng, 32)          # 4 shareable full blocks
    eng = make_engine(model, params, kv_blocks=32, prefix=True)
    sched = ContinuousBatchingScheduler(eng)
    sched.submit(600, sys_prompt + rand_prompt(rng, 6), max_new_tokens=2)
    sched.run_to_completion()                  # prefix now cached
    # two live sequences sharing the cached prefix
    for uid in (601, 602):
        sched.submit(uid, sys_prompt + rand_prompt(rng, 6),
                     max_new_tokens=30)
        for _ in range(3):
            sched.step()
    sm = eng.state_manager
    for uid in (601, 602):
        total = sm.get_sequence(uid).cur_allocated_blocks
        free = eng.freeable_blocks_of(uid)
        assert free < total            # the 4 shared blocks don't count
        assert total - free == 4
    # a sequence holding only private + cache-only blocks frees them all
    sched.cancel(602)
    assert eng.freeable_blocks_of(601) == \
        sm.get_sequence(601).cur_allocated_blocks


def test_preemption_requires_reservation():
    """preemption without reservation would be silently inert (every
    preemption entry point lives on the reservation branch) — rejected
    at config validation and at the engine hook."""
    import pydantic

    from deepspeed_tpu.serving import ServingConfig

    with pytest.raises(pydantic.ValidationError):
        ServingConfig(admission={"reservation": False,
                                 "preemption": {"enabled": True}})
    with pytest.raises(ValueError):
        AdmissionConfig(preemption={"enabled": True})


def test_engine_configure_admission_guard(model_and_params):
    model, params = model_and_params
    eng = make_engine(model, params)
    with pytest.raises(ValueError):
        eng.configure_admission(False, preemption_enabled=True)


def test_config_wiring():
    """``admission:`` mounts on ServingConfig AND DeepSpeedTpuConfig,
    and ``AdmissionConfig.apply`` stamps a ragged engine config."""
    from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig
    from deepspeed_tpu.serving import ServingConfig

    sc = ServingConfig(admission={"reservation": True,
                                  "oversubscription_factor": 2.5,
                                  "preemption": {"enabled": True,
                                                 "victim_policy":
                                                     "most_blocks",
                                                 "max_preemptions_per_seq":
                                                     3}})
    assert sc.admission.active
    ecfg = RaggedInferenceEngineConfig()
    sc.admission.apply(ecfg)
    assert ecfg.admission_reservation
    assert ecfg.admission_oversubscription_factor == 2.5
    assert ecfg.admission_preemption_enabled
    assert ecfg.admission_victim_policy == "most_blocks"
    assert ecfg.admission_max_preemptions_per_seq == 3
    ds = DeepSpeedTpuConfig(**{
        "train_micro_batch_size_per_gpu": 1,
        "admission": {"reservation": True},
        "serving": {"admission": {"reservation": True,
                                  "preemption": {"enabled": True}}}})
    assert ds.admission.reservation
    assert ds.serving.admission.preemption.enabled
    assert not AdmissionConfig().active
