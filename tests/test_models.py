"""Model + sharding tests (reference tests/unit/model_parallelism and
inference v2 model tests; attention numeric test mirrors
tests/unit/ops/accelerators/test_accelerator_forward.py kernel-vs-reference
comparisons)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import build_model, MODEL_CONFIGS
from deepspeed_tpu.models.transformer import (
    CausalLM, TINY_TEST, TransformerConfig, attention_reference, apply_rope,
    rope_table)
from deepspeed_tpu.ops.flash_attention import flash_attention, _attention_xla
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.parallel.sharding import ZeroShardingPlan, tree_shardings


def test_init_and_forward():
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, TINY_TEST.vocab_size)
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_loss_decreases_with_overfit():
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    batch = {"input_ids": jnp.tile(jnp.arange(33)[None], (4, 1))}

    @jax.jit
    def step(params):
        loss, grads = jax.value_and_grad(lambda p: model.loss(p, batch))(params)
        return loss, jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)

    losses = []
    for _ in range(10):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_gpt2_layernorm_learned_pos():
    cfg = dataclasses.replace(MODEL_CONFIGS["gpt2-125m"], num_layers=2,
                              hidden_size=64, intermediate_size=128,
                              num_heads=4, vocab_size=128, max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(1))
    assert "wpe" in params["embed"]
    assert "attn_norm_b" in params["layers"]
    logits = model.apply(params, jnp.zeros((1, 8), jnp.int32))
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_gqa_matches_mha_when_repeated():
    B, T, H, KH, D = 2, 16, 8, 2, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, T, KH, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, T, KH, D)).astype(np.float32))
    out_gqa = attention_reference(q, k, v)
    out_mha = attention_reference(q, jnp.repeat(k, H // KH, axis=2),
                                  jnp.repeat(v, H // KH, axis=2))
    # grouped-einsum GQA reduces in a different order than repeated-KV MHA;
    # only float-associativity noise is allowed
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha),
                               rtol=1e-4, atol=1e-6)


def test_flash_attention_matches_reference():
    B, T, H, D = 2, 64, 4, 32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    out = flash_attention(q, k, v, True, 32, 32)
    ref = _attention_xla(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # gradient path
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, True, 32, 32)))(q)
    gref = jax.grad(lambda q: jnp.sum(_attention_xla(q, k, v, True)))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref), rtol=2e-4, atol=2e-5)


def test_rope_rotation_is_orthogonal():
    cos, sin = rope_table(32, 16, 10000.0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 32, 2, 16)).astype(np.float32))
    y = apply_rope(x, cos, sin)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(y), axis=-1),
                               np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)


def test_param_specs_cover_all_params():
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    assert jax.tree_util.tree_structure(jax.tree.map(lambda _: 0, params)) == \
        jax.tree_util.tree_structure(
            jax.tree.map(lambda _: 0, specs,
                         is_leaf=lambda x: isinstance(x, tuple)))


def test_tp_sharding_on_mlp():
    t = topo.MeshTopology.build(tensor=2, fsdp=2, data=-1)
    topo.set_topology(t)
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    plan = ZeroShardingPlan(t, zero_stage=3, spec_tree=model.param_specs())
    shardings = plan.params(params)
    w_in = shardings["layers"]["w_in"]   # spec: layers, embed, mlp
    assert "tensor" in str(w_in.spec)
    assert "fsdp" in str(w_in.spec)


def test_num_params_formula():
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert model.num_params() == actual


def test_new_family_presets_forward():
    """Each new-family preset builds and runs a tiny-shrunk forward (arch
    switches exercised: qkv_bias, relu+learned, partial rotary + parallel
    residual, alibi + embedding LN, MQA + parallel residual)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.models import MODEL_CONFIGS, CausalLM

    for name in ("qwen2-7b", "opt-1.3b", "pythia-1.4b", "bloom-560m",
                 "falcon-7b"):
        cfg = dataclasses.replace(
            MODEL_CONFIGS[name], vocab_size=128, hidden_size=32,
            intermediate_size=64, num_layers=2,
            num_heads=4,
            num_kv_heads=(1 if MODEL_CONFIGS[name].kv_heads == 1 else 2),
            max_seq_len=64, dtype=jnp.float32)
        model = CausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 128, size=(2, 16)))
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, 128), name
        assert np.isfinite(np.asarray(logits)).all(), name


def test_layer_windows_and_segments():
    """Per-layer sliding-window schedules (Qwen2 mixed full/SWA): scalar
    broadcast, tuple normalization, and the contiguous constant-window
    run segmentation the layer scans compile from."""
    cfg = TransformerConfig(num_layers=4, sliding_window=8)
    assert cfg.layer_windows() == (8, 8, 8, 8)
    assert cfg.window_segments() == ((0, 4, 8),)
    cfg = TransformerConfig(num_layers=4)
    assert cfg.window_segments() == ((0, 4, 0),)
    cfg = TransformerConfig(num_layers=4, sliding_window=(None, None, 8, 8))
    assert cfg.layer_windows() == (0, 0, 8, 8)
    assert cfg.window_segments() == ((0, 2, 0), (2, 2, 8))
    cfg = TransformerConfig(num_layers=4, sliding_window=(4, None, 4, None))
    assert cfg.window_segments() == ((0, 1, 4), (1, 1, 0), (2, 1, 4),
                                     (3, 1, 0))
    with pytest.raises(ValueError, match="entries"):
        TransformerConfig(num_layers=4, sliding_window=(8,)).layer_windows()


def test_mixed_window_forward_matches_manual_mask():
    """A mixed full/SWA schedule through the segmented layer scan equals
    running the same layers with per-layer reference masks; remat composes
    (the window is a static checkpoint arg)."""
    cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                            intermediate_size=64, num_layers=4, num_heads=4,
                            max_seq_len=32, norm="rmsnorm",
                            activation="silu", position="rope",
                            sliding_window=(None, 6, None, 6),
                            attention_impl="reference")
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    out = model.apply(params, tokens)

    # remat path must agree exactly (same program, checkpointed)
    cfg_remat = dataclasses.replace(cfg, remat=True)
    out_remat = CausalLM(cfg_remat).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_remat),
                               atol=1e-6)

    # ground truth: an explicit unrolled per-layer loop with each layer's
    # own window — catches swapped segments / wrong window assignment
    from deepspeed_tpu.models.transformer import _norm, rope_table

    T = tokens.shape[1]
    x = params["embed"]["wte"][tokens].astype(cfg.dtype)
    cos_full, sin_full = rope_table(cfg.max_seq_len, cfg.rot_dim,
                                    cfg.rope_theta)
    cos, sin = cos_full[:T], sin_full[:T]
    for i, win in enumerate(cfg.layer_windows()):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        x, _ = model._block(x, lp, cos, sin, jax.random.PRNGKey(0), True,
                            win)
    x = _norm(x, params["final_norm"]["w"], params["final_norm"].get("b"),
              cfg.norm, cfg.norm_eps)
    expected = model._unembed(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)

    # and the mixed schedule is genuinely distinct from both uniform ones
    full = CausalLM(dataclasses.replace(cfg, sliding_window=None)).apply(
        params, tokens)
    swa = CausalLM(dataclasses.replace(cfg, sliding_window=6)).apply(
        params, tokens)
    assert not np.allclose(np.asarray(out), np.asarray(full))
    assert not np.allclose(np.asarray(out), np.asarray(swa))


def _count_scans(jaxpr):
    """Scan primitives anywhere in a jaxpr — each is one compiled body."""
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            n += 1
        for v in eqn.params.values():
            closed = v if isinstance(v, (list, tuple)) else [v]
            for c in closed:
                if hasattr(c, "jaxpr"):
                    n += _count_scans(c.jaxpr)
    return n


def test_alternating_window_schedule_compiles_one_scan():
    """GPT-Neo-style alternating global/local windows (ISSUE 1 satellite,
    ADVICE.md): one scan switching between the D=2 distinct window bodies
    instead of one scan body per layer — compile cost O(distinct), not
    O(layers). Qwen2-style contiguous runs keep the per-segment split."""
    cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                            intermediate_size=64, num_layers=6, num_heads=2,
                            max_seq_len=64, sliding_window=(0, 8, 0, 8, 0, 8),
                            attention_impl="reference")
    m = CausalLM(cfg)
    params = m.init(jax.random.PRNGKey(0))
    toks = np.zeros((1, 8), np.int32)
    assert _count_scans(jax.make_jaxpr(m.apply)(params, toks).jaxpr) == 1

    m._scan_mode = "segments"        # the old path: one scan per segment
    assert _count_scans(jax.make_jaxpr(m.apply)(params, toks).jaxpr) == 6

    # contiguous two-run schedule (Qwen2 full-then-SWA): segments win (2
    # scans, no switch overhead) — auto must NOT route it through switch
    cfg2 = TransformerConfig(vocab_size=64, hidden_size=32,
                             intermediate_size=64, num_layers=4, num_heads=2,
                             max_seq_len=64, sliding_window=(0, 0, 8, 8),
                             attention_impl="reference")
    m2 = CausalLM(cfg2)
    params2 = m2.init(jax.random.PRNGKey(0))
    assert _count_scans(jax.make_jaxpr(m2.apply)(params2, toks).jaxpr) == 2


def test_alternating_window_switch_path_matches_segments():
    """The switch path must be numerically identical to the per-segment
    path, for both the training forward and the prefill KV path."""
    cfg = TransformerConfig(vocab_size=97, hidden_size=48,
                            intermediate_size=96, num_layers=4, num_heads=4,
                            max_seq_len=64, sliding_window=(0, 8, 0, 8),
                            attention_impl="reference")
    m = CausalLM(cfg)
    params = m.init(jax.random.PRNGKey(1))
    toks = np.random.default_rng(0).integers(0, 97, size=(2, 24))

    m._scan_mode = "segments"
    ref = np.asarray(m.apply(params, toks))
    cache_ref = m.init_cache(2, 32)
    pref_ref, cache_ref = m.prefill(params, jnp.asarray(toks), cache_ref)

    m._scan_mode = "switch"
    np.testing.assert_allclose(np.asarray(m.apply(params, toks)), ref,
                               atol=1e-5, rtol=1e-5)
    cache_sw = m.init_cache(2, 32)
    pref_sw, cache_sw = m.prefill(params, jnp.asarray(toks), cache_sw)
    np.testing.assert_allclose(np.asarray(pref_sw), np.asarray(pref_ref),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(cache_sw["k"]),
                               np.asarray(cache_ref["k"]),
                               atol=1e-5, rtol=1e-5)
