"""Cross-process serving fabric (ISSUE 15, docs/SERVING.md "Multi-host
serving").

Covers every layer: the versioned wire codec (byte-exact round trips for
requests, fp32/bf16/int8/fp8 KV slabs + scale planes + dtype stamps,
last_logits; version-mismatch / oversized-frame / garbage refused with
typed errors), the `_routable_ip` advertise satellite, the EngineHandle
protocol (LocalHandle adds nothing; Replica and RemoteHandle both
provide the full surface), block-granularity chunked export/import, and
the end-to-end guarantees: local-vs-remote greedy byte-parity for plain
decode / prefix-cache / speculative / preempt-resume traffic,
cross-process disaggregated handoff parity (fp32 AND int8), transport-
loss failover resuming byte-losslessly on another replica, remote
evacuation, and ``fabric.enabled=false`` being byte-for-byte the
in-process stack. One test drives a REAL subprocess replica server
through ``scripts/serve_replica.py``.
"""

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.serving import (RequestState, ServingConfig,
                                   ServingFrontend, ServingRequest)
from deepspeed_tpu.serving.fabric import codec as fcodec
from deepspeed_tpu.serving.fabric import transport as ftransport
from deepspeed_tpu.serving.fabric.handle import HANDLE_SURFACE, LocalHandle
from deepspeed_tpu.serving.fabric.server import ReplicaServer

VOCAB = 128
MODEL_KW = dict(vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=2, max_seq_len=256, norm="rmsnorm",
                activation="silu", position="rope")
ENGINE_KW = dict(max_ragged_batch_size=128, max_ragged_sequence_count=4,
                 max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
                 max_tracked_sequences=32)
SEED = 0

_model = None
_params = None


def tiny_engine(i=0, **cfg_over):
    """Fresh engine over a module-shared model + seeded params — the
    SAME weights a replica server process builds from the spec (seeded
    ``model.init``), so local-vs-remote parity is byte-meaningful."""
    global _model, _params
    import jax

    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    if _model is None:
        _model = CausalLM(TransformerConfig(**MODEL_KW))
        _params = _model.init(jax.random.PRNGKey(SEED))
    base = dict(ENGINE_KW)
    base.update(cfg_over)
    return InferenceEngineV2(_model, params=_params,
                             config=RaggedInferenceEngineConfig(**base))


def prompts(n, seed, lo=8, hi=24):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(length)).tolist()
            for length in rng.integers(lo, hi, size=n)]


def run_fleet(fe, ps, max_new, timeout=300, request_class=None):
    kw = {"request_class": request_class} if request_class else {}
    hs = [fe.submit(p, max_new_tokens=max_new, **kw) for p in ps]
    assert fe.wait_all(hs, timeout=timeout), [h.state for h in hs]
    return [[ev.token for ev in h.drain()] for h in hs]


def local_reference(ps, max_new, n_replicas=1, **scfg_extra):
    fe = ServingFrontend([tiny_engine(i) for i in range(n_replicas)],
                         ServingConfig(max_queue_depth=64, **scfg_extra))
    try:
        return run_fleet(fe, ps, max_new)
    finally:
        fe.shutdown(drain=False, timeout=5)


class _Servers:
    """N threaded replica servers over real TCP sockets (full codec +
    framing + multiplexing, no subprocess startup cost)."""

    def __init__(self, n, server_config=None, heartbeat_s=0.3, **eng_over):
        self.servers = [
            ReplicaServer(lambda i=i: tiny_engine(i, **eng_over),
                          server_config or ServingConfig(),
                          listen="127.0.0.1:0", replica_id=i,
                          heartbeat_s=heartbeat_s)
            for i in range(n)]
        for s in self.servers:
            s.start()
        self.peers = [f"127.0.0.1:{s.port}" for s in self.servers]

    def stop(self):
        for s in self.servers:
            s.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def fabric_cfg(peers, heartbeat_s=0.3, **extra):
    return ServingConfig(
        max_queue_depth=64,
        fabric={"enabled": True, "peers": list(peers),
                "heartbeat_s": heartbeat_s, "rpc_timeout_s": 60.0},
        **extra)


# ================================================================= codec
class TestCodec:
    def test_array_roundtrip_byte_exact_all_dtypes(self):
        import ml_dtypes

        arrs = {
            "f32": np.random.default_rng(0).normal(size=(2, 3, 4))
                   .astype(np.float32),
            "bf16": np.arange(24, dtype=np.float32).reshape(2, 12)
                    .astype(ml_dtypes.bfloat16),
            "i8": np.random.default_rng(1).integers(-128, 127, size=(3, 5))
                  .astype(np.int8),
            "fp8": (np.random.default_rng(2).normal(size=16) * 10)
                   .astype(ml_dtypes.float8_e4m3fn),
        }
        obj = {"slabs": arrs, "meta": {"dtype": "fp8_e4m3", "n": 3,
                                       "nested": [1, None, "x", 2.5, True]}}
        back = fcodec.decode_frame(fcodec.encode_frame(obj))
        for k, a in arrs.items():
            assert back["slabs"][k].dtype == a.dtype, k
            assert back["slabs"][k].shape == a.shape, k
            assert back["slabs"][k].tobytes() == a.tobytes(), \
                f"{k} slab bytes changed across the wire"
        assert back["meta"] == obj["meta"]

    def test_export_payload_roundtrip_fp32_and_quant(self):
        for quant_dtype in (None, "int8", "fp8_e4m3"):
            over = ({} if quant_dtype is None
                    else {"kv_quant_enabled": True,
                          "kv_quant_dtype": quant_dtype})
            eng = tiny_engine(**over)
            from deepspeed_tpu.inference.v2.scheduler import (
                ContinuousBatchingScheduler)

            sched = ContinuousBatchingScheduler(eng, prefill_only=True)
            sched.submit(1, prompts(1, 3)[0], max_new_tokens=4)
            sched.run_to_completion()
            assert sched.finished[1].finish_reason == "prefilled"
            payload = eng.export_sequence(1)
            payload["last_logits"] = sched.finished[1].last_logits
            back = fcodec.decode_frame(fcodec.encode_frame(payload))
            assert back["kv_quant_dtype"] == payload["kv_quant_dtype"]
            assert back["seen_tokens"] == payload["seen_tokens"]
            for name, slab in payload["slabs"].items():
                assert back["slabs"][name].tobytes() == \
                    np.asarray(slab).tobytes(), (quant_dtype, name)
            assert np.asarray(back["last_logits"]).tobytes() == \
                np.asarray(payload["last_logits"]).tobytes()

    def test_request_wire_roundtrip(self):
        req = ServingRequest([1, 2, 3], 16, 1, 5.0, 9,
                             request_class="batch", shed_rank=1)
        req.push_token(7)
        req.push_token(8)
        req.attempts = 2
        req.no_prefill = True
        back = fcodec.request_from_wire(fcodec.decode_frame(
            fcodec.encode_frame(fcodec.request_to_wire(req))))
        assert back.uid == req.uid
        assert back.prompt_tokens == [1, 2, 3]
        assert back.generated_tokens == [7, 8]
        assert back.n_generated == 2
        assert back.resume_prompt() == req.resume_prompt()
        assert back.remaining_new_tokens == req.remaining_new_tokens
        assert back.max_new_tokens == 16 and back.eos_token_id == 9
        assert back.request_class == "batch" and back.shed_rank == 1
        assert back.attempts == 2 and back.no_prefill
        assert back.deadline_t is not None
        # replayed tokens must NOT re-enter the stream (the previous
        # replica already delivered them)
        assert back._events.empty()

    def test_version_mismatch_typed(self):
        frame = fcodec.encode_frame({"x": 1})
        (hlen,) = struct.unpack(">I", frame[:4])
        header = json.loads(frame[4:4 + hlen].decode())
        header["v"] = 99
        bad = json.dumps(header).encode()
        doctored = struct.pack(">I", len(bad)) + bad + frame[4 + hlen:]
        with pytest.raises(fcodec.VersionMismatch):
            fcodec.decode_frame(doctored)

    def test_oversized_and_garbage_typed(self):
        with pytest.raises(fcodec.FrameTooLarge):
            fcodec.encode_frame({"big": np.zeros(1 << 16)},
                                max_frame_bytes=1024)
        with pytest.raises(fcodec.CodecError):
            fcodec.decode_frame(b"\x00\x00\x00\xffgarbage")
        with pytest.raises(fcodec.CodecError):
            fcodec.decode_frame(b"\x00")
        with pytest.raises(fcodec.CodecError):
            fcodec.encode_frame({"fn": lambda: 1})

    def test_inconsistent_buffer_descriptor_typed(self):
        """nbytes/shape disagreement must be a TYPED CodecError (numpy
        would raise bare ValueError) — the transport reader relies on
        typed refusals to take the dead-connection transition."""
        frame = fcodec.encode_frame({"a": np.arange(9, dtype=np.int8)
                                     .reshape(3, 3)})
        (hlen,) = struct.unpack(">I", frame[:4])
        header = json.loads(frame[4:4 + hlen].decode())
        header["bufs"][0][2] = 4            # lie about nbytes
        bad = json.dumps(header).encode()
        doctored = struct.pack(">I", len(bad)) + bad + frame[4 + hlen:]
        with pytest.raises(fcodec.CodecError):
            fcodec.decode_frame(doctored)

    def test_recv_frame_refuses_oversized_before_alloc(self):
        a, b = socket.socketpair()
        try:
            ftransport.send_frame(a, b"x" * 4096)
            with pytest.raises(fcodec.FrameTooLarge):
                ftransport.recv_frame(b, max_frame_bytes=128)
        finally:
            a.close()
            b.close()

    def test_stale_window_floor_tolerates_compile_pauses(self):
        """A short heartbeat must NOT shrink the staleness window below
        the floor: a healthy peer stalls for seconds inside an XLA
        compile, and reading that as death would kill replicas exactly
        as they warm up. A CLOSED socket still dies instantly."""
        a, b = socket.socketpair()
        conn = ftransport.Connection(a, heartbeat_s=0.05)
        try:
            conn._last_rx = time.monotonic() - 1.0   # 20 heartbeats silent
            assert conn.alive, \
                "silence under the stale floor read as death"
            conn._last_rx = time.monotonic() \
                - ftransport.STALE_FLOOR_S - 1.0
            assert not conn.alive
        finally:
            conn.close()
            b.close()
        a2, b2 = socket.socketpair()
        conn2 = ftransport.Connection(a2, heartbeat_s=0.05)
        conn2.start()
        try:
            b2.close()                       # peer closes: instant death
            deadline = time.monotonic() + 5
            while conn2.alive and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not conn2.alive, "closed socket not detected"
        finally:
            conn2.close()

    def test_payload_chunks_split_and_reassemble(self):
        whole = {"seen_tokens": 10, "n_blocks": 2, "block_size": 8,
                 "kv_quant": False, "kv_quant_dtype": "int8",
                 "slabs": {"k": np.arange(4.0), "v": np.arange(4.0) + 1}}
        meta, chunks = fcodec.payload_chunks(whole)
        assert len(chunks) == 1 and "slabs" not in meta
        back = fcodec.payload_from_chunks(meta, chunks)
        assert np.array_equal(back["slabs"]["k"], whole["slabs"]["k"])
        chunked = dict(whole)
        del chunked["slabs"]
        chunked["chunk_blocks"] = 1
        chunked["chunks"] = [{"k": np.zeros(2), "v": np.ones(2)},
                             {"k": np.zeros(2) + 2, "v": np.ones(2) + 2}]
        meta, chunks = fcodec.payload_chunks(chunked)
        assert len(chunks) == 2
        back = fcodec.payload_from_chunks(meta, chunks)
        assert len(back["chunks"]) == 2
        assert fcodec.payload_from_chunks(None, []) is None


# ============================================================= advertise
class TestAdvertisedAddress:
    def test_wildcard_and_loopback_use_routable_ip(self, monkeypatch):
        from deepspeed_tpu.comm import comm as comm_mod

        monkeypatch.setattr(comm_mod, "_routable_ip", lambda: "10.9.8.7")
        assert ftransport.advertised_address("0.0.0.0", 7001) \
            == "10.9.8.7:7001"
        assert ftransport.advertised_address("", 7002) == "10.9.8.7:7002"
        assert ftransport.advertised_address("127.0.0.1", 7003) \
            == "10.9.8.7:7003"
        # "localhost" resolves to a DIFFERENT machine's loopback on
        # every peer — it must advertise the routable IP too
        assert ftransport.advertised_address("localhost", 7004) \
            == "10.9.8.7:7004"

    def test_explicit_host_passes_through(self):
        assert ftransport.advertised_address("192.168.1.5", 7001) \
            == "192.168.1.5:7001"

    def test_never_loopback_when_route_exists(self):
        from deepspeed_tpu.comm.comm import _routable_ip

        if _routable_ip().startswith("127."):
            pytest.skip("host has no routable interface")
        host = ftransport.advertised_address("0.0.0.0", 1234).rsplit(":",
                                                                     1)[0]
        assert not host.startswith("127.")


# ======================================================= handle protocol
class TestHandleProtocol:
    def test_local_handle_adds_nothing(self):
        """LocalHandle must stay an EMPTY subclass: any override would
        fork the fabric's local path from the plain-Replica disabled
        path."""
        allowed = {"__module__", "__qualname__", "__doc__", "__slots__",
                   "__firstlineno__", "__static_attributes__"}
        extra = set(LocalHandle.__dict__) - allowed
        assert not extra, f"LocalHandle overrides {sorted(extra)}"

    def test_replica_and_remote_provide_the_surface(self):
        fe = ServingFrontend([tiny_engine()],
                             ServingConfig(max_queue_depth=8))
        try:
            rep = fe.router.replicas[0]
            missing = [n for n in HANDLE_SURFACE if not hasattr(rep, n)]
            assert not missing, f"Replica lacks {missing}"
        finally:
            fe.shutdown(drain=False, timeout=5)
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                rh = fe.router.replicas[0]
                assert getattr(rh, "is_remote", False)
                missing = [n for n in HANDLE_SURFACE
                           if not hasattr(rh, n)]
                assert not missing, f"RemoteHandle lacks {missing}"
                assert rh.engine.model.cfg.max_seq_len \
                    == MODEL_KW["max_seq_len"]
            finally:
                fe.shutdown(drain=False, timeout=5)


# ==================================================== chunked export/import
class TestChunkedExport:
    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_chunked_export_import_byte_parity(self, quant):
        over = ({} if quant is None
                else {"kv_quant_enabled": True, "kv_quant_dtype": quant})
        from deepspeed_tpu.inference.v2.scheduler import (
            ContinuousBatchingScheduler)

        src = tiny_engine(**over)
        sched = ContinuousBatchingScheduler(src, prefill_only=True)
        prompt = prompts(1, 7, lo=30, hi=31)[0]     # several blocks
        sched.submit(1, prompt, max_new_tokens=2)
        sched.run_to_completion()
        whole = src.export_sequence(1)
        chunked = src.export_sequence(1, chunk_blocks=1)
        assert chunked["chunk_blocks"] == 1
        assert len(chunked["chunks"]) == whole["n_blocks"]
        # chunk content == the whole-slab content, byte for byte
        for name, slab in whole["slabs"].items():
            glued = np.concatenate(
                [np.asarray(c[name]) for c in chunked["chunks"]], axis=1)
            assert glued.tobytes() == np.asarray(slab).tobytes(), name
        # chunked import reproduces the pool content exactly
        tokens = prompt[:whole["seen_tokens"]]
        dst_a = tiny_engine(**over)
        dst_a.import_sequence(5, whole, tokens=tokens)
        dst_b = tiny_engine(**over)
        dst_b.import_sequence(5, chunked, tokens=tokens)
        for name in dst_a.state_manager.kv_cache:
            a = np.asarray(dst_a.state_manager.kv_cache[name])
            b = np.asarray(dst_b.state_manager.kv_cache[name])
            assert a.tobytes() == b.tobytes(), name

    def test_chunk_count_mismatch_refused(self):
        src = tiny_engine()
        from deepspeed_tpu.inference.v2.scheduler import (
            ContinuousBatchingScheduler)

        sched = ContinuousBatchingScheduler(src, prefill_only=True)
        prompt = prompts(1, 8, lo=20, hi=21)[0]
        sched.submit(1, prompt, max_new_tokens=2)
        sched.run_to_completion()
        payload = src.export_sequence(1, chunk_blocks=1)
        payload["chunks"] = payload["chunks"][:-1]      # drop a chunk
        dst = tiny_engine()
        tokens = prompt[:payload["seen_tokens"]]
        with pytest.raises(ValueError, match="chunks cover"):
            dst.import_sequence(5, payload, tokens=tokens)
        assert not dst.state_manager.tracked_sequences


# ============================================================ wire refusal
class TestWireRefusal:
    def test_hello_version_mismatch_is_typed_and_non_fatal(self):
        with _Servers(1) as srv:
            conn = ftransport.dial(srv.peers[0], heartbeat_s=0.0)
            try:
                with pytest.raises(ftransport.FabricError,
                                   match="version_mismatch"):
                    conn.call("hello", {"codec_version": 99,
                                        "role": "mixed"}, timeout_s=30)
                # the server survived the refusal: a correct hello on
                # the same connection succeeds
                info = conn.call("hello",
                                 {"codec_version": fcodec.CODEC_VERSION,
                                  "role": "mixed"}, timeout_s=120)
                assert info["max_seats"] \
                    == ENGINE_KW["max_ragged_sequence_count"]
            finally:
                conn.close()

    def test_remote_handle_does_not_retry_version_mismatch(self,
                                                           monkeypatch):
        from deepspeed_tpu.serving.fabric import remote as fremote

        monkeypatch.setattr(fcodec, "CODEC_VERSION", 99)
        monkeypatch.setattr(fremote, "CODEC_VERSION", 99)
        with _Servers(1) as srv:
            cfg = fabric_cfg(srv.peers)
            t0 = time.monotonic()
            with pytest.raises(fcodec.VersionMismatch):
                fremote.RemoteHandle(0, srv.peers[0],
                                     cfg.fabric).connect()
            assert time.monotonic() - t0 < 10, \
                "version mismatch burned the whole retry budget"


# ========================================================== remote parity
class TestRemoteParity:
    def test_disabled_fabric_is_byte_identical(self):
        ps = prompts(4, 11)
        ref = local_reference(ps, 6)
        fe = ServingFrontend([tiny_engine()], ServingConfig(
            max_queue_depth=64, fabric={"enabled": False}))
        try:
            got = run_fleet(fe, ps, 6)
            from deepspeed_tpu.serving.replica import Replica

            assert type(fe.router.replicas[0]) is Replica
        finally:
            fe.shutdown(drain=False, timeout=5)
        assert got == ref, "fabric.enabled=false diverged from the " \
                           "in-process stack"

    def test_plain_decode_parity(self):
        ps = prompts(6, 12)
        ref = local_reference(ps, 6)
        with _Servers(2) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                got = run_fleet(fe, ps, 6)
                snap = fe.metrics_snapshot()
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref, "remote handles broke greedy byte-parity"
        assert snap["requests_completed"] == len(ps)
        assert snap["rpc_call_s"]["count"] >= len(ps)    # assigns timed
        assert snap["tokens_generated"] == sum(len(g) for g in got)

    def test_custom_sample_fn_refused_with_peers(self):
        """A sampler callable cannot cross the process boundary — a
        fleet that would sample differently per replica must be refused
        at construction, not discovered in production."""
        with _Servers(1) as srv:
            with pytest.raises(ValueError, match="sample_fn"):
                ServingFrontend([tiny_engine()], fabric_cfg(srv.peers),
                                sample_fn=lambda logits: 0)

    def test_cancel_crosses_the_wire(self):
        """RequestHandle.cancel on a remotely-running request must reach
        the server (the flag lives on a mirror, not a shared object) and
        terminate the stream CANCELLED."""
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                h = fe.submit(prompts(1, 24)[0], max_new_tokens=200)
                deadline = time.monotonic() + 60
                while h._req.n_generated < 2 \
                        and time.monotonic() < deadline:
                    time.sleep(0.002)
                assert h._req.n_generated >= 2, "stream never started"
                h.cancel()
                assert h._req.wait(30), "cancel never terminated the " \
                                        "remote stream"
                assert h.state == RequestState.CANCELLED
                # the server replica freed the sequence: it accepts a
                # full-budget follow-up immediately
                got = run_fleet(fe, prompts(1, 25), 4)
                assert got == local_reference(prompts(1, 25), 4)
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_mixed_local_and_remote_fleet(self):
        ps = prompts(6, 13)
        ref = local_reference(ps, 5)
        with _Servers(1) as srv:
            fe = ServingFrontend([tiny_engine()], fabric_cfg(srv.peers))
            try:
                assert len(fe.router.replicas) == 2
                got = run_fleet(fe, ps, 5)
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref

    def test_prefix_cache_parity_and_forwarded_counters(self):
        sys_prompt = prompts(1, 14, lo=40, hi=41)[0]
        ps = [sys_prompt + p for p in prompts(4, 15, lo=4, hi=8)]
        ref = local_reference(ps, 4)
        server_cfg = ServingConfig(
            prefix_cache={"enabled": True, "max_cached_blocks": 0})
        with _Servers(1, server_config=server_cfg) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                # sequential: the first request's blocks must register
                # in the server's prefix index before the repeats match
                got = []
                for p in ps:
                    got.extend(run_fleet(fe, [p], 4))
                # forwarded engine counters need a status tick
                deadline = time.monotonic() + 10
                snap = fe.metrics_snapshot()
                while snap["prefix_blocks_hit"] == 0 \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                    snap = fe.metrics_snapshot()
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref, "remote prefix cache broke parity"
        assert snap["prefix_blocks_hit"] > 0, \
            "server-side prefix counters never forwarded"

    def test_speculative_parity(self):
        ps = [p * 3 for p in prompts(4, 16, lo=6, hi=10)]  # ngram food
        ref = local_reference(ps, 8)
        server_cfg = ServingConfig(
            speculative={"enabled": True, "mode": "ngram",
                         "max_draft_tokens": 4})
        with _Servers(1, server_config=server_cfg) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                got = run_fleet(fe, ps, 8)
                deadline = time.monotonic() + 10
                snap = fe.metrics_snapshot()
                while snap["spec_tokens_proposed"] == 0 \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                    snap = fe.metrics_snapshot()
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref, "remote speculation broke greedy parity"
        assert snap["spec_tokens_proposed"] > 0

    def test_preempt_resume_parity(self):
        """Overload a small remote KV pool under reservation admission +
        preemption (batch victims yield to interactive work): sequences
        spill/resume server-side and the streams stay byte-identical to
        an uncontended local reference."""
        ps_batch = prompts(4, 17, lo=60, hi=61)
        ps_int = prompts(8, 27, lo=60, hi=61)
        ref_batch = local_reference(ps_batch, 24)
        ref_int = local_reference(ps_int, 4)
        server_cfg = ServingConfig(
            prefix_cache={"enabled": True}, kv_tier={"enabled": True},
            admission={"reservation": True,
                       "oversubscription_factor": 3.0,
                       "preemption": {"enabled": True}})
        with _Servers(1, server_config=server_cfg, kv_blocks=14,
                      max_ragged_sequence_count=8) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                bh = [fe.submit(p, max_new_tokens=24,
                                request_class="batch") for p in ps_batch]
                time.sleep(0.6)
                ih = [fe.submit(p, max_new_tokens=4,
                                request_class="interactive")
                      for p in ps_int]
                assert fe.wait_all(bh + ih, timeout=300), \
                    [h.state for h in bh + ih]
                got_batch = [[ev.token for ev in h.drain()] for h in bh]
                got_int = [[ev.token for ev in h.drain()] for h in ih]
                deadline = time.monotonic() + 10
                snap = fe.metrics_snapshot()
                while snap["sequences_preempted"] == 0 \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                    snap = fe.metrics_snapshot()
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got_batch == ref_batch and got_int == ref_int, \
            "remote preempt/resume broke parity"
        assert snap["sequences_preempted"] > 0, \
            "pool sized to preempt never preempted — parity vacuous"
        assert snap["sequences_resumed"] > 0


# ==================================================== cross-process handoff
class TestCrossProcessHandoff:
    @pytest.mark.parametrize("quant", [None, "int8", "fp8_e4m3"])
    def test_disagg_handoff_parity(self, quant):
        ps = prompts(4, 18, lo=12, hi=20)
        ref = local_reference(ps, 5)
        disagg = {"enabled": True, "roles": ["prefill", "decode"],
                  "handoff": {"enabled": True, "max_staged": 8,
                              "chunk_blocks": 1}}
        server_cfg = ServingConfig(
            disaggregation=disagg,
            kv_quant=({"enabled": True, "dtype": quant}
                      if quant else {"enabled": False}))
        with _Servers(2, server_config=server_cfg) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers,
                                                disaggregation=disagg))
            try:
                got = run_fleet(fe, ps, 5, timeout=300)
                snap = fe.metrics_snapshot()
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert snap["handoffs_started"] > 0, "no handoffs staged"
        deadline_completed = snap["handoffs_completed"]
        assert deadline_completed > 0, \
            "no cross-process handoff completed — parity vacuous"
        assert got == ref, \
            f"cross-process KV handoff (quant={quant}) broke parity"

    def test_asymmetric_frame_bounds_degrade_not_disconnect(self):
        """Sender and receiver bounds are negotiated in hello: a KV
        payload over the peer's receive bound must die at ENCODE (typed
        → re-prefill fallback), never at the peer's reader (which would
        kill the connection and loop the request through failover)."""
        ps = prompts(3, 26, lo=60, hi=61)       # ~8 blocks of KV each
        ref = local_reference(ps, 4)
        disagg = {"enabled": True, "roles": ["prefill", "decode"],
                  "handoff": {"enabled": True, "max_staged": 8}}
        server_cfg = ServingConfig(
            disaggregation=disagg,
            # tiny RECEIVE bound: a whole-prompt staged payload cannot
            # fit one frame (the RPC envelopes still do)
            fabric={"max_frame_bytes": 1 << 16})
        with _Servers(2, server_config=server_cfg) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers,
                                                disaggregation=disagg))
            try:
                got = run_fleet(fe, ps, 4, timeout=300)
                snap = fe.metrics_snapshot()
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref, "frame-bound degrade broke parity"
        assert snap["handoff_fallbacks"] > 0, \
            "payloads fit the tiny bound — degrade path untested"
        assert snap["handle_disconnects"] == 0, \
            "an oversized payload killed a connection"

    def test_assign_rpc_failure_is_replica_failure(self, monkeypatch):
        """A failed/timed-out assign RPC is AMBIGUOUS (the server may
        have adopted the request) — the handle must go DEAD so the
        server-side ghost is cancelled on disconnect, never requeue into
        a possible duplicate execution."""
        from deepspeed_tpu.serving.fabric.transport import RPCTimeout

        ps = prompts(2, 28)
        ref = local_reference(ps, 4)
        srv = _Servers(2)
        fe = ServingFrontend([], fabric_cfg(
            srv.peers,
            fault_tolerance={"enabled": True, "max_retries": 3,
                             "restart_backoff_s": 0.05}))
        try:
            victim = fe.router.replicas[0]
            real_call = victim._call

            def flaky_call(method, payload=None, timeout_s=None,
                           _first=[True]):
                if method == "assign" and _first[0]:
                    _first[0] = False
                    raise RPCTimeout("injected assign timeout")
                return real_call(method, payload, timeout_s=timeout_s)

            monkeypatch.setattr(victim, "_call", flaky_call)
            got = run_fleet(fe, ps, 4, timeout=120)
            snap = fe.metrics_snapshot()
            from deepspeed_tpu.serving.replica import ReplicaState

            assert victim.state == ReplicaState.DEAD
            assert snap["handle_disconnects"] >= 1
        finally:
            fe.shutdown(drain=False, timeout=5)
            srv.stop()
        assert got == ref, "assign-failure handling broke parity"

    def test_streamed_chunked_handoff_local_parity(self):
        """The chunked staging path for LOCAL handles: chunk_blocks=1
        must stream per-block and stay byte-lossless."""
        ps = prompts(4, 19, lo=16, hi=24)
        ref = local_reference(ps, 5)
        disagg = {"enabled": True, "roles": ["prefill", "decode"],
                  "decode_reserve_tokens": 8,
                  "handoff": {"enabled": True, "max_staged": 8,
                              "chunk_blocks": 1}}
        fe = ServingFrontend([tiny_engine(0), tiny_engine(1)],
                             ServingConfig(max_queue_depth=64,
                                           disaggregation=disagg))
        try:
            got = run_fleet(fe, ps, 5, timeout=300)
            snap = fe.metrics_snapshot()
        finally:
            fe.shutdown(drain=False, timeout=5)
        assert snap["handoffs_completed"] > 0
        assert got == ref, "chunked local handoff broke parity"


# ============================================================== failover
class TestTransportLossFailover:
    def test_kill_server_mid_stream_resumes_losslessly(self):
        ps = prompts(2, 20, lo=8, hi=12)
        ref = local_reference(ps, 160)
        srv = _Servers(2)
        fe = ServingFrontend([], fabric_cfg(
            srv.peers,
            fault_tolerance={"enabled": True, "max_retries": 3,
                             "restart_backoff_s": 0.1}))
        try:
            hs = [fe.submit(p, max_new_tokens=160) for p in ps]
            deadline = time.monotonic() + 60
            victim = None
            while time.monotonic() < deadline and victim is None:
                for h in hs:
                    # a long stream is live on this replica: kill its
                    # server NOW, mid-decode
                    if h._req.n_generated >= 2 \
                            and h._req.replica_id is not None:
                        victim = h._req.replica_id
                        break
                else:
                    time.sleep(0.002)
            assert victim is not None, "no stream ever started"
            srv.servers[victim].stop()
            assert fe.wait_all(hs, timeout=120), [h.state for h in hs]
            got = [[ev.token for ev in h.drain()] for h in hs]
            # detection rides the router health sweep — give it a beat
            deadline = time.monotonic() + 15
            snap = fe.metrics_snapshot()
            while snap["handle_disconnects"] == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
                snap = fe.metrics_snapshot()
            kinds = [e["kind"] for e in fe.journal.events()]
        finally:
            fe.shutdown(drain=False, timeout=5)
            srv.stop()
        assert got == ref, "transport-loss failover broke byte parity"
        assert snap["handle_disconnects"] >= 1
        assert "replica_disconnected" in kinds
        # the victim's in-flight requests failed over (stream spliced)
        assert snap["requests_failed_over"] >= 1
        assert any(h.attempts > 1 for h in hs)

    def test_supervisor_reconnects_after_server_returns(self):
        """DEAD handle → supervisor restart → fresh handle + server-side
        reset: the slot serves again and the journal records the
        reconnect."""
        srv = _Servers(1, heartbeat_s=0.2)
        fe = ServingFrontend([tiny_engine()], fabric_cfg(
            srv.peers, heartbeat_s=0.2,
            fault_tolerance={"enabled": True, "max_retries": 3,
                             "restart_backoff_s": 0.05,
                             "max_restarts_in_window": 10}))
        try:
            # sever the handle's transport (server stays up): the handle
            # goes DEAD and the supervisor re-dials the same server
            handle = fe.router.replica_by_id(1)
            handle._conn.close("injected transport loss")
            deadline = time.monotonic() + 30
            reconnected = False
            while time.monotonic() < deadline and not reconnected:
                reconnected = fe.journal.count("replica_reconnected") > 0
                time.sleep(0.05)
            assert reconnected, "supervisor never re-attached the peer"
            ps = prompts(2, 21)
            got = run_fleet(fe, ps, 4)
            assert got == local_reference(ps, 4)
        finally:
            fe.shutdown(drain=False, timeout=5)
            srv.stop()


# ============================================================ evacuation
class TestRemoteEvacuation:
    def test_remove_remote_replica_mid_stream(self):
        ps = prompts(2, 22, lo=8, hi=12)
        ref = local_reference(ps, 160)
        srv = _Servers(2)
        fe = ServingFrontend([], fabric_cfg(
            srv.peers,
            fault_tolerance={"enabled": True, "max_retries": 3}))
        try:
            hs = [fe.submit(p, max_new_tokens=160) for p in ps]
            deadline = time.monotonic() + 60
            victim = None
            while time.monotonic() < deadline and victim is None:
                for h in hs:
                    if h._req.n_generated >= 2 \
                            and h._req.replica_id is not None:
                        victim = h._req.replica_id
                        break
                else:
                    time.sleep(0.002)
            assert victim is not None, "no stream ever started"
            fe.remove_replica(victim, timeout_s=30.0)
            assert fe.wait_all(hs, timeout=120), [h.state for h in hs]
            got = [[ev.token for ev in h.drain()] for h in hs]
            snap = fe.metrics_snapshot()
        finally:
            fe.shutdown(drain=False, timeout=5)
            srv.stop()
        assert got == ref, "remote evacuation broke byte parity"
        assert snap["requests_evacuated"] >= 1
        assert len(fe.router.replicas) == 1


# ============================================================ subprocess
class TestSubprocessReplica:
    def test_subprocess_server_decode_parity(self, tmp_path):
        """The real thing: scripts/serve_replica.py in its own process
        (own JAX runtime), adopted as a RemoteHandle — greedy streams
        must match the in-process fleet byte for byte."""
        spec = {"model": MODEL_KW, "engine": ENGINE_KW, "seed": SEED,
                "serving": {}}
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(spec))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "serve_replica.py"),
             "--spec", str(spec_path), "--listen", "127.0.0.1:0",
             "--loopback-ok"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env)
        try:
            line = proc.stdout.readline()       # blocks until jax is up
            assert line.startswith("FABRIC_LISTENING "), line
            addr = line.split()[1]
            ps = prompts(4, 23)
            ref = local_reference(ps, 5)
            fe = ServingFrontend([], fabric_cfg([addr], heartbeat_s=1.0))
            try:
                got = run_fleet(fe, ps, 5, timeout=300)
            finally:
                fe.shutdown(drain=False, timeout=5)
            assert got == ref, \
                "subprocess replica broke greedy byte-parity"
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


# ==================================================== mixed-model fleet
class TestMixedModelFleet:
    """One frontend, two model families (docs/SERVING.md "Multi-model &
    multi-tenant serving"): a ``models:`` registry builds heterogeneous
    replica pools — here fam_a served ONLY by a subprocess replica
    server and fam_b by a local engine plus a second subprocess — and
    the router keys every dispatch on the request's model_id. Misroute
    is shown impossible structurally (every completed request ran on a
    replica of its own pool; the hello exchange refuses a peer hosting
    a different model) and per-model greedy parity pins each pool's
    weights to a solo single-model fleet built from the same spec."""

    FAM_B_MODEL = dict(MODEL_KW, hidden_size=48, intermediate_size=96)
    FAM_B_SEED = 7

    def _spawn(self, tmp_path, name, model_kw, seed, model_id):
        spec = {"model": model_kw, "engine": ENGINE_KW, "seed": seed,
                "model_id": model_id, "serving": {}}
        path = tmp_path / f"{name}.json"
        path.write_text(json.dumps(spec))
        return subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "serve_replica.py"),
             "--spec", str(path), "--listen", "127.0.0.1:0",
             "--loopback-ok"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=dict(os.environ, JAX_PLATFORMS="cpu"))

    @staticmethod
    def _addr(proc):
        line = proc.stdout.readline()
        assert line.startswith("FABRIC_LISTENING "), line
        return line.split()[1]

    @staticmethod
    def _reap(proc):
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    def test_two_families_one_frontend_parity_and_routing(self, tmp_path):
        from deepspeed_tpu.serving.config import ModelSpec
        from deepspeed_tpu.serving.frontend import engine_from_model_spec

        procs = [
            self._spawn(tmp_path, "fam_a", MODEL_KW, SEED, "fam_a"),
            self._spawn(tmp_path, "fam_b", self.FAM_B_MODEL,
                        self.FAM_B_SEED, "fam_b"),
        ]
        try:
            addr_a, addr_b = self._addr(procs[0]), self._addr(procs[1])
            ps = {m: prompts(3, seed) for m, seed in
                  (("fam_a", 31), ("fam_b", 32))}
            # solo single-model references from the SAME specs
            ref = {"fam_a": local_reference(ps["fam_a"], 5)}
            spec_b = ModelSpec(model=self.FAM_B_MODEL, engine=ENGINE_KW,
                               seed=self.FAM_B_SEED)
            fe_ref = ServingFrontend([engine_from_model_spec(spec_b)],
                                     ServingConfig(max_queue_depth=64))
            try:
                ref["fam_b"] = run_fleet(fe_ref, ps["fam_b"], 5)
            finally:
                fe_ref.shutdown(drain=False, timeout=5)

            fe = ServingFrontend([], ServingConfig(
                max_queue_depth=64,
                fabric={"enabled": True, "peers": [],
                        "heartbeat_s": 1.0, "rpc_timeout_s": 60.0},
                models={
                    "fam_a": {"model": MODEL_KW, "engine": ENGINE_KW,
                              "seed": SEED, "replicas": 0,
                              "peers": [addr_a]},
                    "fam_b": {"model": self.FAM_B_MODEL,
                              "engine": ENGINE_KW,
                              "seed": self.FAM_B_SEED, "replicas": 1,
                              "peers": [addr_b]},
                }))
            try:
                by_id = {r.replica_id: getattr(r, "model_id", "default")
                         for r in fe.router.replicas}
                assert sorted(by_id.values()) == \
                    ["fam_a", "fam_b", "fam_b"], by_id
                hs = {m: [fe.submit(p, max_new_tokens=5, model=m)
                          for p in ps[m]] for m in ("fam_a", "fam_b")}
                assert fe.wait_all(hs["fam_a"] + hs["fam_b"],
                                   timeout=300), \
                    [h.state for m in hs for h in hs[m]]
                for m, handles in hs.items():
                    # structural misroute impossibility: every request
                    # ran on a replica of ITS model's pool
                    for h in handles:
                        assert by_id[h._req.replica_id] == m, \
                            f"{m} request served by " \
                            f"{by_id[h._req.replica_id]} replica"
                    got = [[ev.token for ev in h.drain()]
                           for h in handles]
                    assert got == ref[m], \
                        f"{m} greedy parity vs its solo fleet broke"
                report = fe.health_report()
                assert sorted(r["model"] for r in report["replicas"]) \
                    == ["fam_a", "fam_b", "fam_b"]
            finally:
                fe.shutdown(drain=False, timeout=5)
        finally:
            for p in procs:
                self._reap(p)

    def test_peer_hosting_wrong_model_refused(self):
        """Adopting a peer into a pool whose model it does not host is
        a config error, refused typed at the hello — NOT retried (the
        mismatch is permanent) and never silently misrouted."""
        with _Servers(1) as srv:        # advertises model_id "default"
            with pytest.raises(fcodec.ModelMismatch, match="hosts model"):
                ServingFrontend([], ServingConfig(
                    max_queue_depth=64,
                    fabric={"enabled": True, "peers": [],
                            "heartbeat_s": 0.3, "rpc_timeout_s": 30.0},
                    models={"fam_a": {"model": MODEL_KW,
                                      "engine": ENGINE_KW,
                                      "replicas": 0,
                                      "peers": srv.peers}}))


# ========================================= fleet KV locality digests
class TestFabricPrefixDigest:
    """ISSUE 17 (docs/SERVING.md "Fleet KV locality"): the prefix digest
    rides the status stream as an OPTIONAL field. A peer that sends one
    participates in affinity routing; a peer that never does is
    cache-blind — zero credit, never refused."""

    def test_status_digest_codec_roundtrip(self):
        ev = {"t": "ev", "ev": "status", "state": "healthy",
              "counters": {},
              "prefix_digest": [0, 12345, -(2 ** 63), 2 ** 63 - 1]}
        back = fcodec.decode_frame(fcodec.encode_frame(ev))
        assert back["prefix_digest"] == ev["prefix_digest"]

    def test_status_without_digest_decodes_to_absent(self):
        # the historical status event: no digest field at all — the
        # consumer must read absence (None), not an empty list
        ev = {"t": "ev", "ev": "status", "state": "healthy",
              "counters": {}}
        back = fcodec.decode_frame(fcodec.encode_frame(ev))
        assert "prefix_digest" not in back
        assert back.get("prefix_digest") is None

    def test_digestless_peer_is_cache_blind_not_refused(self):
        """Server with affinity off (the historical server): its status
        events carry no digest. An affinity-enabled frontend must adopt
        it, route to it, and score it at zero credit — degraded, never
        an error."""
        sys_prompt = prompts(1, 31, lo=40, hi=41)[0]
        ps = [sys_prompt + p for p in prompts(4, 32, lo=4, hi=8)]
        ref = local_reference(ps, 4)
        with _Servers(1) as srv:        # server affinity: disabled
            fe = ServingFrontend([], fabric_cfg(
                srv.peers,
                affinity={"enabled": True, "refresh_interval_s": 0.05}))
            try:
                got = []
                for p in ps:
                    got.extend(run_fleet(fe, [p], 4))
                time.sleep(0.5)          # status ticks + digest refresh
                remote = fe.router.replicas[0]
                assert remote.prefix_digest() == frozenset()
                assert fe._affinity.digest_of(
                    remote.replica_id) == frozenset()
                st = fe._affinity.stats()
                assert st["hits"] == 0 and st["tokens_saved"] == 0
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref, "digest-less peer broke greedy parity"

    def test_subprocess_peer_digest_earns_affinity_credit(self, tmp_path):
        """The real thing: a serve_replica.py subprocess with affinity +
        prefix cache on. Its digest must arrive via the status stream
        (no new RPC exists to fetch it) and earn affinity credit for
        shared-prefix repeats — with greedy parity intact."""
        spec = {"model": MODEL_KW, "engine": ENGINE_KW, "seed": SEED,
                "serving": {"prefix_cache": {"enabled": True},
                            "affinity": {"enabled": True,
                                         "refresh_interval_s": 0.05}}}
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(spec))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "serve_replica.py"),
             "--spec", str(spec_path), "--listen", "127.0.0.1:0",
             "--loopback-ok"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env)
        try:
            line = proc.stdout.readline()
            assert line.startswith("FABRIC_LISTENING "), line
            addr = line.split()[1]
            sys_prompt = prompts(1, 33, lo=40, hi=41)[0]
            ps = [sys_prompt + p for p in prompts(4, 34, lo=4, hi=8)]
            ref = local_reference(ps, 4)
            fe = ServingFrontend([], fabric_cfg(
                [addr], heartbeat_s=1.0,
                affinity={"enabled": True, "refresh_interval_s": 0.05}))
            try:
                got = []
                for p in ps:             # warm the remote prefix index
                    got.extend(run_fleet(fe, [p], 4))
                remote = fe.router.replicas[0]
                aff = fe._affinity
                deadline = time.monotonic() + 15
                while not aff.digest_of(remote.replica_id) \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                assert aff.digest_of(remote.replica_id), \
                    "peer digest never arrived on the status stream"
                # shared-prefix repeats: the remote digest must now win
                # affinity credit in pick()
                for p in ps:
                    got2 = run_fleet(fe, [p], 4)
                    assert got2 == [ref[ps.index(p)]]
                st = aff.stats()
                assert st["hits"] > 0 and st["tokens_saved"] > 0, st
            finally:
                fe.shutdown(drain=False, timeout=5)
            assert got == ref, "affinity peer broke greedy parity"
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


# ================================================ digest delta stream
class TestDigestDeltas:
    """ISSUE 18: the status stream sends digest add/evict DELTAS under a
    monotonic epoch instead of a full snapshot every tick (full snapshot
    on connect/reset) — wire bytes scale with cache churn, not
    ``digest_max_entries``. Back-compat both ways, the PR 17
    optional-field idiom: an old SERVER keeps sending full snapshots
    (always decoded, replace outright); an old CLIENT never advertises
    ``digest_deltas`` and keeps receiving full snapshots."""

    def _handle(self):
        from deepspeed_tpu.serving.fabric.remote import RemoteHandle

        return RemoteHandle(1, "127.0.0.1:1",
                            fabric_cfg([]).fabric)

    @staticmethod
    def _status(**fields):
        ev = {"t": "ev", "ev": "status", "state": "healthy",
              "thread_alive": True, "occupancy": {}, "param_stats": {},
              "tier_stats": {}, "counters": {}}
        ev.update(fields)
        return ev

    def test_stream_encoder_full_then_deltas(self):
        from deepspeed_tpu.serving.fabric.server import DigestStream

        ds = DigestStream()
        ev = {}
        ds.stamp(ev, {3, 1, 2}, deltas=True)
        assert ev == {"prefix_digest": [1, 2, 3], "digest_epoch": 0}
        ev = {}
        ds.stamp(ev, {3, 1, 2}, deltas=True)
        assert ev == {}, "no churn must send nothing"
        ev = {}
        ds.stamp(ev, {2, 3, 4, 5}, deltas=True)
        assert ev == {"digest_epoch": 1, "digest_add": [4, 5],
                      "digest_del": [1]}
        ds.reset()
        ev = {}
        ds.stamp(ev, {7}, deltas=True)
        assert ev == {"prefix_digest": [7], "digest_epoch": 0}, \
            "reset must restart with a full snapshot"

    def test_stream_encoder_non_opted_client_gets_pr17_shape(self):
        from deepspeed_tpu.serving.fabric.server import DigestStream

        ds = DigestStream()
        for digest in ({1, 2}, {1, 2}, {2, 3}):
            ev = {}
            ds.stamp(ev, digest, deltas=False)
            assert ev == {"prefix_digest": sorted(digest)}, \
                "a non-opted client must get a full snapshot every tick"

    def test_client_applies_deltas_in_sequence(self):
        h = self._handle()
        h._ev_status(self._status(prefix_digest=[1, 2, 3],
                                  digest_epoch=0))
        assert h.prefix_digest() == frozenset({1, 2, 3})
        h._ev_status(self._status(digest_epoch=1, digest_add=[4],
                                  digest_del=[1]))
        assert h.prefix_digest() == frozenset({2, 3, 4})
        h._ev_status(self._status())        # digest-less tick: keep
        assert h.prefix_digest() == frozenset({2, 3, 4})
        h._ev_status(self._status(digest_epoch=2, digest_del=[2]))
        assert h.prefix_digest() == frozenset({3, 4})

    def test_client_drops_to_blind_on_sequence_gap(self):
        h = self._handle()
        h._ev_status(self._status(prefix_digest=[1, 2], digest_epoch=0))
        h._ev_status(self._status(digest_epoch=5, digest_add=[9]))
        assert h.prefix_digest() == frozenset(), \
            "an out-of-sequence delta must drop to cache-blind"
        # the epoch resynced: the NEXT in-sequence delta rebuilds warmth
        h._ev_status(self._status(digest_epoch=6, digest_add=[9]))
        assert h.prefix_digest() == frozenset({9})

    def test_old_server_full_snapshots_still_decode(self):
        """An old peer never numbers its frames: every full snapshot
        replaces outright, and a delta can never (mis)apply."""
        h = self._handle()
        h._ev_status(self._status(prefix_digest=[1, 2]))    # no epoch
        assert h.prefix_digest() == frozenset({1, 2})
        assert h._digest_epoch is None
        h._ev_status(self._status(prefix_digest=[3]))
        assert h.prefix_digest() == frozenset({3})

    def test_new_client_hello_advertises_deltas(self):
        assert self._handle()._hello_payload(False)["digest_deltas"] \
            is True

    def test_end_to_end_deltas_over_real_server(self):
        """Live ReplicaServer + RemoteHandle with affinity on: the
        digest arrives (via the delta stream — the handle advertises
        ``digest_deltas``) and matches what a full snapshot would say."""
        scfg = ServingConfig(prefix_cache={"enabled": True},
                             affinity={"enabled": True,
                                       "refresh_interval_s": 0.05})
        with _Servers(1, server_config=scfg) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers,
                affinity={"enabled": True, "refresh_interval_s": 0.05}))
            try:
                sys_prompt = prompts(1, 41, lo=40, hi=41)[0]
                ps = [sys_prompt + p for p in prompts(3, 42, lo=4, hi=8)]
                for p in ps:
                    run_fleet(fe, [p], 4)
                remote = fe.router.replicas[0]
                deadline = time.monotonic() + 15
                while not remote.prefix_digest() \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                assert remote.prefix_digest(), \
                    "digest never arrived over the delta stream"
                assert remote._digest_epoch is not None, \
                    "a delta-advertising client must get numbered frames"
                want = srv.servers[0].replica.prefix_digest(
                    scfg.affinity.digest_max_entries)
                deadline = time.monotonic() + 10
                while remote.prefix_digest() != want \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                assert remote.prefix_digest() == want, \
                    "delta-reassembled digest diverged from the source"
            finally:
                fe.shutdown(drain=False, timeout=5)


# ==================================================== TP mesh replicas
class TestSubprocessMesh:
    """ISSUE 18 satellite: the serve_replica.py spec's ``mesh:`` field
    builds the hosted engine over a TP MeshTopology slice of the host's
    devices (arxiv 2605.25645's replica-pools-as-mesh-slices shape)."""

    def _spawn(self, tmp_path, mesh, devices):
        spec = {"model": MODEL_KW, "engine": ENGINE_KW, "seed": SEED,
                "mesh": mesh, "serving": {}}
        spec_path = tmp_path / "mesh_spec.json"
        spec_path.write_text(json.dumps(spec))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count="
                             f"{devices}")
        return subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "serve_replica.py"),
             "--spec", str(spec_path), "--listen", "127.0.0.1:0",
             "--loopback-ok"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)

    def test_too_few_devices_is_descriptive_error(self, tmp_path):
        proc = self._spawn(tmp_path, {"tensor": 64}, devices=2)
        try:
            _, err = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
        assert proc.returncode == 2, err
        assert "requires" in err and "64" in err and "2" in err, \
            f"error must say required vs available: {err!r}"

    @pytest.mark.skipif(
        __import__("jax").device_count() < 2,
        reason="TP mesh replica needs >= 2 devices")
    def test_tp2_mesh_replica_decode_parity(self, tmp_path):
        """A tensor=2 mesh replica server must serve byte-identical
        greedy streams to the unsharded in-process fleet."""
        proc = self._spawn(tmp_path, {"tensor": 2, "data": 1}, devices=2)
        try:
            line = proc.stdout.readline()       # blocks until jax is up
            assert line.startswith("FABRIC_LISTENING "), \
                (line, proc.stderr.read() if proc.poll() is not None
                 else "")
            addr = line.split()[1]
            ps = prompts(4, 43)
            ref = local_reference(ps, 5)
            fe = ServingFrontend([], fabric_cfg([addr], heartbeat_s=1.0))
            try:
                got = run_fleet(fe, ps, 5, timeout=300)
            finally:
                fe.shutdown(drain=False, timeout=5)
            assert got == ref, "TP-mesh replica broke greedy byte-parity"
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


# ================================================= transport chaos edges
class TestTransportChaosEdges:
    """ISSUE 20: the wire's nastiest edges, driven deterministically by
    the fabric chaos injector (serving/fabric/chaos.py) instead of
    hand-rolled socket torture. Every failure must stay TYPED — the
    transport's existing guarantees are exercised, never widened."""

    @staticmethod
    def _pair(name_a="chaos-a", name_b="chaos-b", **kw):
        sa, sb = socket.socketpair()
        a = ftransport.Connection(sa, name=name_a, **kw)
        b = ftransport.Connection(sb, name=name_b, **kw)
        a.start()
        b.start()
        return a, b

    def test_chaos_disabled_is_zero_interposition(self):
        from deepspeed_tpu.serving.fabric import chaos as fchaos

        assert fchaos.installed() is None
        a, b = self._pair()
        try:
            # no injector → the historical branch-free path
            assert a._chaos is None and b._chaos is None
        finally:
            a.close()
            b.close()
        # installed but non-matching schedule → still zero interposition
        inj = fchaos.install(fchaos.NetworkFaultInjector(
            [{"kind": "latency", "link": "some-other-link",
              "delay_s": 1.0}]))
        try:
            a, b = self._pair()
            try:
                assert a._chaos is None and b._chaos is None
            finally:
                a.close()
                b.close()
            assert inj.fired() == []
        finally:
            fchaos.uninstall()
        # and the default encode is the v1 wire, byte for byte: sealing
        # a frame elsewhere must not perturb the unsealed path
        obj = {"t": "ev", "x": 1, "a": np.arange(8, dtype=np.int32)}
        plain = fcodec.encode_frame(obj)
        sealed = fcodec.encode_frame(obj, crc=True)
        assert fcodec.encode_frame(obj) == plain
        assert sealed != plain and len(sealed) > len(plain)

    def test_half_open_blackhole_hits_staleness_not_socket(self,
                                                           monkeypatch):
        """The classic gray failure: rx silently discarded, tx fine,
        socket open. Only the staleness detector may call it — and the
        OTHER side (whose rx still flows) must stay alive."""
        from deepspeed_tpu.serving.fabric import chaos as fchaos

        monkeypatch.setattr(ftransport, "STALE_FLOOR_S", 0.6)
        fchaos.install(fchaos.NetworkFaultInjector(
            [{"kind": "blackhole", "link": "half-open-a", "dir": "rx"}]))
        try:
            a, b = self._pair("half-open-a", "half-open-b",
                              heartbeat_s=0.1)
            try:
                deadline = time.monotonic() + 10
                while a.alive and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert not a.alive, \
                    "blackholed rx never tripped the staleness detector"
                # half-open: a's SOCKET never died — staleness, not EOF
                assert not a._dead
                # asymmetric: b still receives a's pings → b stays alive
                assert b.alive
                assert fchaos.installed().fired("blackhole",
                                                "half-open-a")
            finally:
                a.close()
                b.close()
        finally:
            fchaos.uninstall()

    def test_partial_frame_at_connection_death_is_typed(self):
        """drop_conn with partial_bytes leaves the peer a length prefix
        promising more bytes than ever arrive: its reader must die with
        the typed mid-frame ConnectionLost, never hang or misparse."""
        from deepspeed_tpu.serving.fabric import chaos as fchaos

        fchaos.install(fchaos.NetworkFaultInjector(
            [{"kind": "drop_conn", "link": "partial-a", "at_frame": 1,
              "partial_bytes": 3, "count": 1}]))
        try:
            got = []
            done = threading.Event()
            sa, sb = socket.socketpair()
            a = ftransport.Connection(sa, name="partial-a")

            def on_ev(m):
                got.append(m.get("k"))

            b = ftransport.Connection(
                sb, name="partial-b", on_event=on_ev,
                on_close=lambda reason: done.set())
            a.start()
            b.start()
            try:
                a.send({"t": "ev", "k": 0})     # frame 0 flows
                a.send({"t": "ev", "k": 1})     # frame 1: partial + kill
                assert done.wait(10), "peer reader never died"
                assert "EOF inside a fabric frame" in b.close_reason
                assert "chaos" in a.close_reason
                assert got == [0]
            finally:
                a.close()
                b.close()
        finally:
            fchaos.uninstall()

    def test_oversized_and_garbage_header_mid_stream(self):
        """A garbage length prefix over the bound is refused BEFORE
        allocation (FrameTooLarge kills the connection); an in-bound
        but undecodable body on an UNSEALED link is protocol divergence
        (typed death, never limping on)."""
        # oversized announced length
        sa, sb = socket.socketpair()
        dead = threading.Event()
        b = ftransport.Connection(sb, name="garbage-b",
                                  max_frame_bytes=4096,
                                  on_close=lambda r: dead.set())
        b.start()
        try:
            sa.sendall(ftransport.struct.pack(">I", 1 << 30))
            assert dead.wait(10)
            assert "FrameTooLarge" in b.close_reason
        finally:
            b.close()
            sa.close()
        # garbage body after a GOOD frame (mid-stream, not a bad dial)
        sa, sb = socket.socketpair()
        got = []
        dead = threading.Event()
        b = ftransport.Connection(sb, name="garbage-c",
                                  max_frame_bytes=4096,
                                  on_event=lambda m: got.append(m["k"]),
                                  on_close=lambda r: dead.set())
        b.start()
        try:
            good = fcodec.encode_frame({"t": "ev", "k": 7})
            sa.sendall(ftransport.struct.pack(">I", len(good)) + good)
            junk = b"\x00\x00\x00\x08not-json"
            sa.sendall(junk)
            assert dead.wait(10)
            assert "undecodable frame" in b.close_reason
            assert got == [7]
        finally:
            b.close()
            sa.close()

    def test_heartbeat_survives_throttled_link(self, monkeypatch):
        """A thin pipe is not a dead pipe: with the drip rate still
        letting ~heartbeat-sized frames through under the staleness
        window, both ends must stay alive for the whole throttle."""
        from deepspeed_tpu.serving.fabric import chaos as fchaos

        monkeypatch.setattr(ftransport, "STALE_FLOOR_S", 1.0)
        fchaos.install(fchaos.NetworkFaultInjector(
            [{"kind": "throttle", "link": "thin-*", "dir": "tx",
              "bytes_per_s": 2048.0}]))
        try:
            a, b = self._pair("thin-a", "thin-b", heartbeat_s=0.2)
            try:
                deadline = time.monotonic() + 2.0
                while time.monotonic() < deadline:
                    assert a.alive and b.alive, \
                        "throttled-but-flowing link read as dead"
                    time.sleep(0.1)
                assert fchaos.installed().fired("throttle")
            finally:
                a.close()
                b.close()
        finally:
            fchaos.uninstall()

    def test_crc_corrupt_frame_is_single_refusal_not_death(self):
        """Partition tolerance on a sealed link: one flipped bit =
        one refused frame (typed, counted, on_corrupt fires) — the
        connection and every other frame on it survive."""
        from deepspeed_tpu.serving.fabric import chaos as fchaos

        fchaos.install(fchaos.NetworkFaultInjector(
            [{"kind": "corrupt", "link": "crc-a", "dir": "tx",
              "at_frame": 1, "count": 1, "where": "payload"}]))
        try:
            got = []
            corrupt_cb = []
            sa, sb = socket.socketpair()
            a = ftransport.Connection(sa, name="crc-a")
            b = ftransport.Connection(
                sb, name="crc-b",
                on_event=lambda m: got.append(m.get("k")),
                on_corrupt=lambda: corrupt_cb.append(1))
            a.crc_tx = True
            b.crc_rx = True
            a.start()
            b.start()
            try:
                payload = np.arange(64, dtype=np.int32)
                for k in range(3):
                    a.send({"t": "ev", "k": k, "buf": payload})
                deadline = time.monotonic() + 10
                while len(got) < 2 and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert got == [0, 2], got
                assert b.frames_corrupt == 1
                assert corrupt_cb == [1]
                assert b.alive and not b._dead, \
                    "a single corrupt frame killed a sealed connection"
            finally:
                a.close()
                b.close()
        finally:
            fchaos.uninstall()
