"""Chaos suite for the serving fault-tolerance layer (ISSUE 5).

Deterministic fault injection (serving/faults.py) drives the full stack
on the CPU mesh: replicas crash/wedge mid-stream on schedule, the
supervisor restarts them with backoff (or parks them via the circuit
breaker), and every accepted request must still complete with greedy
tokens byte-identical to an unfaulted run — the transparent-failover
contract (docs/SERVING.md "Fault tolerance"). Queue-level brownout and
the injector itself are unit-tested without engines.
"""

import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving import (AdmissionQueue, FaultInjector,
                                   FinishReason, InjectedFault, Priority,
                                   Rejected, RequestState, ServingConfig,
                                   ServingFrontend, serving_metrics)

VOCAB = 128

_model = None
_params = None


def tiny_engine(i=0):
    """Fresh engine over a module-shared model/params (what an
    ``engine_factory`` does in production: same weights, fresh KV)."""
    global _model, _params
    if _model is None:
        _model = CausalLM(TransformerConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, max_seq_len=128, norm="rmsnorm",
            activation="silu", position="rope"))
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=4,
        max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
        max_tracked_sequences=16)
    eng = InferenceEngineV2(_model, params=_params, config=vcfg)
    _params = eng.params
    return eng


def prompts(n, seed, lo=8, hi=24):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(l)).tolist()
            for l in rng.integers(lo, hi, size=n)]


def greedy_reference(ps, max_new):
    """Unfaulted single-replica run: the byte-parity baseline."""
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=64))
    try:
        hs = [fe.submit(p, max_new_tokens=max_new) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        return [[ev.token for ev in h.drain()] for h in hs]
    finally:
        fe.shutdown(drain=False, timeout=5)


def ft_config(**over):
    """Fast-recovery fault-tolerance block for tests."""
    ft = {"enabled": True, "max_retries": 3, "restart_backoff_s": 0.05,
          "restart_backoff_max_s": 0.2, "supervisor_poll_s": 0.02,
          "restart_window_s": 60.0, "max_restarts_in_window": 5}
    ft.update(over)
    return ft


def wait_metric(fe, name, value, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fe.metrics_snapshot().get(name, 0) >= value:
            return True
        time.sleep(0.02)
    return False


# ------------------------------------------------------------- injector
def test_injector_schedule_fires_deterministically():
    inj = FaultInjector([
        {"kind": "crash", "replica": 0, "at_step": 2},
        {"kind": "wedge", "replica": 1, "at_step": 0, "duration_s": 0.0,
         "count": 2},
    ])
    inj.on_step(0, 0)
    inj.on_step(0, 1)                      # below at_step: nothing
    with pytest.raises(InjectedFault):
        inj.on_step(0, 2)
    inj.on_step(0, 3)                      # count=1: fired, now inert
    inj.on_step(1, 0)                      # wedge (0s) fires twice, then
    inj.on_step(1, 1)                      # stops
    inj.on_step(1, 2)
    fired = inj.fired_events()
    assert [(k, r, i) for k, r, i, _ in fired] == [
        ("crash", 0, 2), ("wedge", 1, 0), ("wedge", 1, 1)]


def test_injector_seeded_step_range_is_reproducible():
    sched = [{"kind": "crash", "replica": 0, "at_step_range": [2, 40]}]
    a = FaultInjector(sched, seed=7)
    b = FaultInjector(sched, seed=7)
    c = FaultInjector(sched, seed=8)
    assert a.events[0].at_step == b.events[0].at_step
    assert 2 <= a.events[0].at_step <= 40
    assert any(FaultInjector(sched, seed=s).events[0].at_step
               != a.events[0].at_step for s in range(20)), \
        "seed never changes the drawn step"


def test_injector_count_zero_fires_every_time():
    inj = FaultInjector([{"kind": "crash", "replica": 0, "at_step": 1,
                          "count": 0}])
    for step in (1, 2, 5):
        with pytest.raises(InjectedFault):
            inj.on_step(0, step)


def test_injector_rejects_malformed_events():
    with pytest.raises(ValueError):
        FaultInjector([{"kind": "meteor", "replica": 0, "at_step": 0}])
    with pytest.raises(ValueError):
        FaultInjector([{"kind": "crash", "replica": 0}])      # no at_step
    with pytest.raises(ValueError):
        FaultInjector([{"kind": "put_error", "replica": 0}])  # no at_put


def test_engine_proxy_injects_only_put_faults():
    class Eng:
        config = "cfg-sentinel"

        def put(self, uids, chunks):
            return ("ok", uids)

    inj = FaultInjector([
        {"kind": "put_error", "replica": 0, "at_put": 1},
        {"kind": "slow_forward", "replica": 1, "at_put": 0,
         "duration_s": 0.05},
    ])
    wrapped = inj.wrap_engine(Eng(), 0)
    assert wrapped is not inj.wrap_engine(Eng(), 5), "sanity"
    assert inj.wrap_engine(Eng(), 5).__class__ is Eng, \
        "unfaulted replica must get the raw engine, not a proxy"
    assert wrapped.config == "cfg-sentinel"          # delegation
    assert wrapped.put([1], [[2]]) == ("ok", [1])    # put 0 clean
    with pytest.raises(InjectedFault):
        wrapped.put([1], [[2]])                      # put 1 injected
    assert wrapped.put([1], [[2]]) == ("ok", [1])    # one-shot
    slow = inj.wrap_engine(Eng(), 1)
    t0 = time.monotonic()
    slow.put([1], [[2]])
    assert time.monotonic() - t0 >= 0.05             # latency injected


# ------------------------------------------------------------- brownout
def ServingRequest_(prompt, max_new, priority, deadline_s):
    from deepspeed_tpu.serving import ServingRequest

    return ServingRequest(prompt, max_new, priority, deadline_s, None)


def test_brownout_sheds_lowest_urgency_queued_work():
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=4, metrics=reg, brownout_threshold=0.5)
    highs = [ServingRequest_([1] * 4, 4, Priority.HIGH, 60.0)
             for _ in range(2)]
    lows = [ServingRequest_([1] * 4, 4, Priority.LOW, None)
            for _ in range(2)]
    for r in highs + lows:
        q.offer(r)
    # half the fleet died: depth shrinks to ceil(4*0.4)=2, the two LOW/
    # no-deadline requests are shed first — HIGHs survive untouched
    q.set_healthy_fraction(0.4)
    assert len(q) == 2
    for r in lows:
        assert r.state == RequestState.REJECTED
        assert r.finish_reason == FinishReason.BROWNOUT
    for r in highs:
        assert r.state == RequestState.QUEUED
    snap = reg.snapshot()
    assert snap["requests_shed_brownout"] == 2
    assert snap["brownout_active"] == 1.0
    # recovery: full depth again, gauge drops
    q.set_healthy_fraction(1.0)
    assert reg.snapshot()["brownout_active"] == 0.0
    q.offer(ServingRequest_([1] * 4, 4, Priority.LOW, None))
    assert len(q) == 3


def test_brownout_offer_displaces_less_urgent_or_sheds_incoming():
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=4, metrics=reg, brownout_threshold=0.6)
    q.set_healthy_fraction(0.5)          # effective depth 2
    low = ServingRequest_([1] * 4, 4, Priority.LOW, None)
    norm = ServingRequest_([1] * 4, 4, Priority.NORMAL, 60.0)
    q.offer(low)
    q.offer(norm)
    # a HIGH arrival outranks the queued LOW: LOW is displaced
    high = ServingRequest_([1] * 4, 4, Priority.HIGH, 10.0)
    q.offer(high)
    assert low.state == RequestState.REJECTED
    assert low.finish_reason == FinishReason.BROWNOUT
    assert len(q) == 2
    # another LOW arrival outranks nothing queued: it is the one shed
    with pytest.raises(Rejected) as ei:
        q.offer(ServingRequest_([1] * 4, 4, Priority.LOW, None))
    assert ei.value.reason == FinishReason.BROWNOUT
    # failover requeue stays exempt even in brownout (admitted work)
    retried = ServingRequest_([1] * 4, 4, Priority.LOW, None)
    assert q.requeue(retried) is True
    assert len(q) == 3


def test_brownout_never_evicts_failover_requeued_work():
    """A retried request (attempts > 1) already streamed tokens on a
    replica that died; brownout victim selection must skip it — both the
    shrink sweep and offer-time displacement — or failover would not be
    lossless exactly when capacity is degraded."""
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=4, metrics=reg, brownout_threshold=0.6)
    retried = [ServingRequest_([1] * 4, 4, Priority.LOW, None)
               for _ in range(2)]
    for r in retried:
        r.attempts = 2
        assert q.requeue(r)
    fresh = ServingRequest_([1] * 4, 4, Priority.LOW, None)
    q.offer(fresh)
    # shrink to effective depth 2 (3 queued): only the FRESH low goes
    q.set_healthy_fraction(0.5)
    assert fresh.state == RequestState.REJECTED
    assert fresh.finish_reason == FinishReason.BROWNOUT
    assert all(r.state == RequestState.QUEUED for r in retried)
    # offer-time displacement: a HIGH arrival cannot displace retried
    # LOWs — with no sheddable victim the incoming request is admitted
    # (depth-exempt, like requeue itself)
    high = ServingRequest_([1] * 4, 4, Priority.HIGH, 10.0)
    q.offer(high)
    assert all(r.state == RequestState.QUEUED for r in retried)
    assert len(q) == 3


# --------------------------------------------------- end-to-end failover
def test_crash_failover_resumes_stream_losslessly():
    """Single supervised replica, crash mid-decode: the in-flight streams
    splice across the restart — one uninterrupted, byte-identical token
    stream per request, with attempts > 1 visible on the handle."""
    ps = prompts(3, seed=1)
    ref = greedy_reference(ps, max_new=6)
    scfg = ServingConfig(
        max_queue_depth=32, fault_tolerance=ft_config(),
        faults={"enabled": True, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 3}]})
    fe = ServingFrontend([tiny_engine()], scfg, engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=6) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in hs)
        got = []
        for h in hs:
            evs = [ev for ev in h.drain()]
            # spliced stream: contiguous indexes, no duplicates/gaps
            assert [ev.index for ev in evs] == list(range(len(evs)))
            got.append([ev.token for ev in evs])
        assert got == ref, "failover resume broke greedy byte-parity"
        assert any(h.attempts > 1 for h in hs), \
            "crash at step 3 failed over nothing"
        snap = fe.metrics_snapshot()
        assert snap["requests_failed_over"] >= 1
        assert snap["replica_restarts"] == 1
        assert snap["requests_failed"] == 0
        assert fe.supervisor.restart_log[0]["recovery_s"] > 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_chaos_kill_one_of_two_replicas_mid_burst(tmp_path):
    """The acceptance scenario: 2 replicas, fault injection kills one
    mid-stream. Every accepted request completes with byte-identical
    greedy tokens vs an unfaulted run, the dead replica is restarted
    (replica_restarts + a flight-recorder dump), and service never
    deadlocks."""
    ps = prompts(8, seed=2)
    ref = greedy_reference(ps, max_new=6)
    scfg = ServingConfig(
        max_queue_depth=32, fault_tolerance=ft_config(),
        telemetry={"enabled": True, "dump_dir": str(tmp_path)},
        faults={"enabled": True, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 3}]})
    fe = ServingFrontend([tiny_engine(), tiny_engine()], scfg,
                         engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=6) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in hs)
        got = [[ev.token for ev in h.drain()] for h in hs]
        assert got == ref, "chaos run lost greedy byte-parity"
        snap = fe.metrics_snapshot()
        assert snap["replica_restarts"] >= 1
        assert snap["requests_failed"] == 0
        assert snap["replicas_parked"] == 0
        # flight recorder: the replica death and/or the restart dumped
        dumps = list(tmp_path.glob("flightrec_*.json"))
        assert dumps, "no flight-recorder dump for the chaos incident"
        assert any("restart" in p.name or "error" in p.name
                   for p in dumps)
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_put_error_injection_takes_engine_fault_path():
    """`engine.put` raising (proxy injection) must look exactly like a
    real engine fault: replica dies, requests fail over, service
    recovers."""
    ps = prompts(2, seed=3)
    ref = greedy_reference(ps, max_new=4)
    scfg = ServingConfig(
        max_queue_depth=16, fault_tolerance=ft_config(),
        faults={"enabled": True, "schedule": [
            {"kind": "put_error", "replica": 0, "at_put": 2}]})
    fe = ServingFrontend([tiny_engine()], scfg, engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=4) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert [[ev.token for ev in h.drain()] for h in hs] == ref
        assert fe.metrics_snapshot()["replica_restarts"] == 1
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_circuit_breaker_parks_repeatedly_crashing_replica():
    """Replica 0 crashes every time it works; after max_restarts_in_window
    crashes the slot is parked (no restart loop), capacity_alarm raises,
    and the surviving replica keeps serving — shed load, no deadlock."""
    ps = prompts(4, seed=4)
    scfg = ServingConfig(
        max_queue_depth=32,
        fault_tolerance=ft_config(max_restarts_in_window=2, max_retries=5),
        faults={"enabled": True, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 0, "count": 0}]})
    fe = ServingFrontend([tiny_engine(), tiny_engine()], scfg,
                         engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=4) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in hs)
        assert wait_metric(fe, "replica_restarts", 1), \
            "first crash never produced a restart"
        # second wave: the restarted (idle, least-loaded) replica 0 takes
        # work again, crashes again → circuit breaker parks the slot
        late = [fe.submit(p, max_new_tokens=4) for p in prompts(4, seed=5)]
        assert fe.wait_all(late, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in late)
        assert wait_metric(fe, "replicas_parked", 1), \
            "crashing replica was never parked"
        snap = fe.metrics_snapshot()
        assert snap["capacity_alarm"] == 1.0
        assert snap["replica_restarts"] == 1      # 2nd crash parks
        # the parked fleet still serves new traffic on the survivor
        tail = [fe.submit(p, max_new_tokens=4) for p in prompts(3, seed=12)]
        assert fe.wait_all(tail, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in tail)
        assert all(h._req.replica_id == 1 for h in tail)
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_failover_bounded_by_max_retries():
    """A request whose every attempt dies is failed terminally once
    max_retries is exhausted — retry storms are bounded."""
    scfg = ServingConfig(
        max_queue_depth=16,
        fault_tolerance=ft_config(max_retries=1, max_restarts_in_window=10),
        faults={"enabled": True, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 0, "count": 0}]})
    fe = ServingFrontend([tiny_engine()], scfg, engine_factory=tiny_engine)
    try:
        h = fe.submit(prompts(1, seed=6)[0], max_new_tokens=4)
        assert h._req.wait(120), "request never reached a terminal state"
        assert h.state == RequestState.FAILED
        assert h.finish_reason == FinishReason.ERROR
        assert h.attempts == 2               # 1 original + 1 retry
        assert fe.metrics_snapshot()["requests_failed_over"] == 1
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_all_parked_fails_queued_and_new_requests_fast():
    """Once every slot is parked nothing is coming back: queued work is
    failed with "no_replicas" (not left to rot until its deadline) and
    new submissions fail fast the same way."""
    scfg = ServingConfig(
        max_queue_depth=16,
        fault_tolerance=ft_config(max_restarts_in_window=1, max_retries=5),
        faults={"enabled": True, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 0, "count": 0}]})
    fe = ServingFrontend([tiny_engine()], scfg, engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=4) for p in prompts(3, seed=7)]
        assert fe.wait_all(hs, timeout=120), \
            "parked fleet left requests hanging"
        assert all(h.state == RequestState.FAILED for h in hs)
        assert all(h.finish_reason == FinishReason.NO_REPLICAS
                   for h in hs)
        assert wait_metric(fe, "replicas_parked", 1)
        h = fe.submit(prompts(1, seed=8)[0], max_new_tokens=4)
        assert h._req.wait(60)
        assert h.finish_reason == FinishReason.NO_REPLICAS
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_wedge_recovers_on_fresh_engine():
    """A wedged replica (worker blocked in a 'device call') is detected
    by the watchdog, its requests fail over, and the supervisor restarts
    the slot on a FRESH engine (the stuck thread owns the old one)."""
    ps = prompts(2, seed=9)
    ref = greedy_reference(ps, max_new=4)
    # wedge_timeout_s must stay ABOVE the worst single-step XLA compile
    # (the documented sizing rule) or the watchdog kills the restarted
    # replica mid-compile; ~1s/step on this tiny CPU model → 2.5s budget
    scfg = ServingConfig(
        max_queue_depth=16, wedge_timeout_s=2.5,
        fault_tolerance=ft_config(),
        faults={"enabled": True, "schedule": [
            {"kind": "wedge", "replica": 0, "at_step": 1,
             "duration_s": 6.0}]})
    fe = ServingFrontend([tiny_engine()], scfg, engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=4) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in hs)
        assert [[ev.token for ev in h.drain()] for h in hs] == ref
        assert fe.metrics_snapshot()["replica_restarts"] >= 1
    finally:
        fe.shutdown(drain=False, timeout=5)


@pytest.mark.slow
def test_wedge_without_engine_factory_parks_slot():
    """No engine factory + a worker thread still stuck inside the engine:
    the supervisor must refuse to reuse that engine (two threads, one KV
    pool) and park the slot instead — safety beats availability."""
    scfg = ServingConfig(
        max_queue_depth=16, wedge_timeout_s=0.2,
        fault_tolerance=ft_config(max_retries=1),
        faults={"enabled": True, "schedule": [
            {"kind": "wedge", "replica": 0, "at_step": 1,
             "duration_s": 8.0}]})
    fe = ServingFrontend([tiny_engine()], scfg)   # NO engine_factory
    try:
        h = fe.submit(prompts(1, seed=10)[0], max_new_tokens=4)
        assert h._req.wait(120), "wedged fleet left the request hanging"
        assert h.state == RequestState.FAILED
        assert wait_metric(fe, "replicas_parked", 1, timeout=30), \
            "unsalvageable slot was not parked"
        assert fe.metrics_snapshot()["replica_restarts"] == 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_faults_disabled_is_byte_identical_and_unhooked():
    """faults.enabled=false (default): no injector, no engine proxy, no
    failover callback when fault_tolerance is also off — byte-for-byte
    the old serving behavior."""
    eng = tiny_engine()
    fe = ServingFrontend([eng], ServingConfig(max_queue_depth=16))
    try:
        assert fe.injector is None
        assert fe.supervisor is None
        assert fe.router.replicas[0].engine is eng      # no proxy
        assert fe.router.replicas[0]._on_failover is None
        ps = prompts(2, seed=11)
        hs = [fe.submit(p, max_new_tokens=4) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        got = [[ev.token for ev in h.drain()] for h in hs]
    finally:
        fe.shutdown(drain=False, timeout=5)
    assert got == greedy_reference(ps, max_new=4)


def test_brownout_wired_from_fault_tolerance_config():
    scfg = ServingConfig(max_queue_depth=8,
                         fault_tolerance=ft_config(brownout_threshold=0.5))
    fe = ServingFrontend([tiny_engine()], scfg)
    try:
        assert fe.admission.brownout_threshold == 0.5
    finally:
        fe.shutdown(drain=False, timeout=5)
    # ft disabled → brownout stays off even if a threshold is set
    fe2 = ServingFrontend([tiny_engine()], ServingConfig(
        max_queue_depth=8,
        fault_tolerance={"enabled": False, "brownout_threshold": 0.5}))
    try:
        assert fe2.admission.brownout_threshold == 0.0
    finally:
        fe2.shutdown(drain=False, timeout=5)
