"""Training chaos suite: preemption-safe self-healing training
(runtime/resilience.py, docs/TRAINING.md "Fault tolerance").

The training counterpart of tests/test_fault_tolerance.py: a seeded
fault injector kills/wedges/poisons a supervised train run at scripted
steps and the suite asserts recovery — including the hard contract that
an interrupted+resumed run reproduces the uninterrupted loss curve
byte-for-byte and lands on identical final params.
`TIER1_CHAOS_TRAIN=1 scripts/tier1.sh` smokes exactly this file.
"""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.parallel.topology as topo
from deepspeed_tpu.models import build_model
from deepspeed_tpu.runtime.dataloader import DeepSpeedTpuDataLoader
from deepspeed_tpu.runtime.resilience import (InjectedTrainFault,
                                              ResilienceConfig, StepWatchdog,
                                              TrainFaultInjector,
                                              TrainingSupervisor)

N_STEPS = 8


def tiny_data(n=64, seq=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(n, seq + 1),
                                      dtype=np.int64)}


def make_config(save_dir, faults=None, **res_over):
    res = {"enabled": True, "save_dir": str(save_dir),
           "save_interval_steps": 2, "restart_backoff_s": 0.01,
           "restart_backoff_jitter": 0.0, "watchdog_enabled": False,
           "faults": faults or {"enabled": False}}
    res.update(res_over)
    return {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_max_lr": 1e-3,
                                 "warmup_num_steps": 5}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": -1, "fsdp": 2},
        "steps_per_print": 10**9,
        "resilience": res,
    }


def build_engine(save_dir, faults=None, data=None, **res_over):
    topo.reset_topology()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config=make_config(save_dir, faults, **res_over),
        training_data=data if data is not None else tiny_data())
    return engine


def params_of(engine):
    return [np.asarray(l) for l in jax.tree.leaves(engine.state.params)]


def assert_same_params(a, b):
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """Uninterrupted supervised run: per-step losses + final params —
    the parity baseline every chaos scenario is judged against."""
    d = tmp_path_factory.mktemp("ref")
    engine = build_engine(d)
    sup = TrainingSupervisor(engine=engine)
    r = sup.run(N_STEPS)
    assert r["status"] == "completed" and r["completed_steps"] == N_STEPS
    return {"losses": sup.losses_by_step(), "params": params_of(engine)}


# ------------------------------------------------------------- injector units
class TestInjector:
    def test_schedule_validation(self):
        with pytest.raises(ValueError, match="unknown train fault kind"):
            TrainFaultInjector([{"kind": "meteor", "at_step": 1}])
        with pytest.raises(ValueError, match="needs at_step"):
            TrainFaultInjector([{"kind": "crash"}])

    def test_crash_raises_and_counts(self):
        inj = TrainFaultInjector([{"kind": "crash", "at_step": 3}])
        assert inj.on_step(2) == []
        with pytest.raises(InjectedTrainFault):
            inj.on_step(3)
        # count=1: fired once, never again (the restarted run passes)
        assert inj.on_step(4) == []
        assert [e[:2] for e in inj.fired_events()] == [("crash", 3)]

    def test_seeded_step_range_is_deterministic(self):
        a = TrainFaultInjector([{"kind": "crash",
                                 "at_step_range": [10, 100]}], seed=7)
        b = TrainFaultInjector([{"kind": "crash",
                                 "at_step_range": [10, 100]}], seed=7)
        c = TrainFaultInjector([{"kind": "crash",
                                 "at_step_range": [10, 100]}], seed=8)
        assert a.events[0].at_step == b.events[0].at_step
        assert 10 <= a.events[0].at_step <= 100
        assert (a.events[0].at_step != c.events[0].at_step
                or a.seed != c.seed)

    def test_coscheduled_events_survive_a_crash(self):
        """A crash raises LAST: sigterm/nan_grads scheduled at the same
        step are delivered via the handler first, not silently consumed
        by the raise (their fired count would otherwise be burned)."""
        inj = TrainFaultInjector([
            {"kind": "sigterm", "at_step": 5},
            {"kind": "crash", "at_step": 5}])
        seen = []
        with pytest.raises(InjectedTrainFault):
            inj.on_step(5, handler=lambda ev: seen.append(ev.kind))
        assert seen == ["sigterm"]

    def test_count_zero_fires_every_time(self):
        inj = TrainFaultInjector([{"kind": "nan_grads", "at_step": 2,
                                   "count": 0}])
        for step in (2, 3, 4):
            evs = inj.on_step(step)
            assert [e.kind for e in evs] == ["nan_grads"]

    def test_disabled_config_builds_nothing(self):
        cfg = ResilienceConfig(faults={"enabled": False, "schedule": [
            {"kind": "crash", "at_step": 1}]})
        assert cfg.faults.build_injector() is None


# ------------------------------------------------------------ watchdog units
class TestWatchdog:
    def test_auto_baseline_arms_after_min_samples(self):
        wd = StepWatchdog(step_timeout_s=0.0, factor=10.0, min_samples=3)
        assert wd.timeout_s() is None
        for dt in (0.01, 0.02, 0.03):
            wd.step_end(dt)
        assert wd.timeout_s() == pytest.approx(0.2)

    def test_fixed_floor_combines_with_median(self):
        """The documented contract: max(step_timeout_s, factor x rolling
        median) — the fixed value is a floor, not an override that turns
        the adaptive threshold off."""
        wd = StepWatchdog(step_timeout_s=0.5, factor=10.0, min_samples=3)
        assert wd.timeout_s() == 0.5        # floor alone before arming
        for dt in (0.1, 0.1, 0.1):
            wd.step_end(dt)
        assert wd.timeout_s() == pytest.approx(1.0)     # max(0.5, 10x0.1)
        wd2 = StepWatchdog(step_timeout_s=5.0, factor=10.0, min_samples=3)
        for dt in (0.1, 0.1, 0.1):
            wd2.step_end(dt)
        assert wd2.timeout_s() == 5.0       # floor dominates a low median

    def test_fixed_timeout_detects_wedge(self):
        wd = StepWatchdog(poll_s=0.01, step_timeout_s=0.05)
        wd.start()
        try:
            wd.step_begin()
            assert wd.wedged.wait(2.0), "watchdog missed the wedged step"
        finally:
            wd.stop()

    def test_completed_steps_do_not_trip(self):
        wd = StepWatchdog(poll_s=0.01, step_timeout_s=0.05)
        wd.start()
        try:
            for _ in range(5):
                wd.step_begin()
                wd.step_end(0.001)
            import time
            time.sleep(0.15)
            assert not wd.wedged.is_set()
        finally:
            wd.stop()

    def test_curriculum_recompile_step_is_exempt(self):
        """A step that changes the curriculum difficulty recompiles
        (minutes vs a sub-second rolling median): the supervisor exempts
        exactly that step from the wedge bracket so a healthy run is not
        parked mid-compile."""
        class Sched:
            def get_difficulty(self, step):
                return 8 if step < 5 else 16

        class WithCurriculum:
            curriculum_scheduler = Sched()

        class NoCurriculum:
            curriculum_scheduler = None

        expect = TrainingSupervisor._expect_recompile
        assert expect(WithCurriculum(), 4)          # 4 -> 5 boundary
        assert not expect(WithCurriculum(), 3)      # steady difficulty
        assert not expect(WithCurriculum(), 6)
        assert not expect(NoCurriculum(), 4)
        assert not expect(object(), 4)              # no scheduler attr

        class Broken:
            class curriculum_scheduler:             # noqa: N801
                @staticmethod
                def get_difficulty(step):
                    raise RuntimeError("no custom fn")

        # a broken schedule fails inside train_batch with its real
        # error, never inside the probe
        assert not expect(Broken(), 4)


# ------------------------------------------------------- shared restart policy
class TestRestartPolicy:
    def test_backoff_breaker_and_window(self):
        import random

        from deepspeed_tpu.utils.restart import RestartPolicy

        p = RestartPolicy(1.0, 8.0, 0.0, 3, 100.0, random.Random(0))
        assert p.record_failure(0.0) == (1, 1.0)    # base
        assert p.record_failure(1.0) == (2, 2.0)    # doubled
        assert p.record_failure(2.0) == (3, None)   # breaker trips

        # failures age out of the sliding window
        p2 = RestartPolicy(1.0, 8.0, 0.0, 3, 10.0, random.Random(0))
        p2.record_failure(0.0)
        p2.record_failure(1.0)
        assert p2.record_failure(50.0) == (1, 1.0)  # first two aged out
        assert p2.count() == 1 and p2.last_failure_time() == 50.0

        # backoff is capped
        p3 = RestartPolicy(1.0, 2.5, 0.0, 10, 100.0, random.Random(0))
        p3.record_failure(0.0)
        p3.record_failure(0.1)
        assert p3.record_failure(0.2)[1] == 2.5     # min(4.0, cap)

    def test_jitter_is_seeded(self):
        import random

        from deepspeed_tpu.utils.restart import RestartPolicy

        a = RestartPolicy(1.0, 8.0, 0.5, 10, 100.0, random.Random(7))
        b = RestartPolicy(1.0, 8.0, 0.5, 10, 100.0, random.Random(7))
        seq_a = [a.record_failure(t)[1] for t in (0.0, 1.0, 2.0)]
        seq_b = [b.record_failure(t)[1] for t in (0.0, 1.0, 2.0)]
        assert seq_a == seq_b                       # deterministic
        assert 1.0 <= seq_a[0] <= 1.5               # jitter in [0, 50%]


# ------------------------------------------------------- dataloader resume
class TestDataloaderState:
    def _loader(self, **kw):
        kw.setdefault("batch_size", 8)
        kw.setdefault("seed", 11)
        return DeepSpeedTpuDataLoader(tiny_data(n=40, seq=8), **kw)

    def test_mid_epoch_resume_continues_exactly(self):
        a = self._loader()
        it = iter(a)
        consumed = [next(it) for _ in range(3)]
        del consumed
        sd = a.state_dict()
        assert sd["batches_yielded"] == 3
        b = self._loader()
        b.load_state_dict(sd)
        cont_a = [next(it)["input_ids"] for _ in range(2)]
        it_b = iter(b)
        cont_b = [next(it_b)["input_ids"] for _ in range(2)]
        for x, y in zip(cont_a, cont_b):
            np.testing.assert_array_equal(x, y)

    def test_resume_across_epoch_boundary(self):
        a = self._loader()
        stream_a = []
        it = iter(a)
        for _ in range(7):       # 5 batches/epoch: crosses into epoch 1
            try:
                stream_a.append(next(it)["input_ids"])
            except StopIteration:
                it = iter(a)
                stream_a.append(next(it)["input_ids"])
        sd = a.state_dict()
        b = self._loader()
        b.load_state_dict(sd)
        it_b = iter(b)
        nxt_a = next(it)["input_ids"]
        nxt_b = next(it_b)["input_ids"]
        np.testing.assert_array_equal(nxt_a, nxt_b)
        # epochs shuffle differently (seed + epoch), so the resumed
        # epoch-1 batch must differ from the epoch-0 batch at that slot
        assert not np.array_equal(stream_a[6], stream_a[1])

    def test_plain_reiteration_unchanged(self):
        """No load_state_dict = historical behavior: a fresh __iter__
        restarts the epoch even after a partial pass."""
        a = self._loader()
        first = next(iter(a))["input_ids"]
        again = next(iter(a))["input_ids"]
        np.testing.assert_array_equal(first, again)

    def test_state_mismatch_rejected(self):
        a = self._loader()
        sd = a.state_dict()
        b = self._loader(seed=99)
        with pytest.raises(ValueError, match="seed"):
            b.load_state_dict(sd)
        # shard identity: a position over order[i::2] means nothing on a
        # 1-shard loader — changed process counts must fail loudly
        sd2 = dict(a.state_dict(), num_shards=2)
        with pytest.raises(ValueError, match="num_shards"):
            self._loader().load_state_dict(sd2)
        # a grown/shrunk dataset reshuffles into a different permutation:
        # the saved position would fast-forward through the wrong stream
        sd3 = dict(a.state_dict(), dataset_len=9999)
        with pytest.raises(ValueError, match="dataset_len"):
            self._loader().load_state_dict(sd3)
        # drop_last changes which batches an epoch yields: a position
        # saved under one setting silently forks under the other
        sd4 = dict(a.state_dict(), drop_last=False)
        with pytest.raises(ValueError, match="drop_last"):
            self._loader().load_state_dict(sd4)

    def test_unresumable_sources_raise(self):
        gen = ({"input_ids": np.zeros((2, 4), np.int64)} for _ in range(3))
        lo = DeepSpeedTpuDataLoader(gen, batch_size=2)
        with pytest.raises(NotImplementedError):
            lo.state_dict()
        # loading into an unresumable loader must fail loudly too — the
        # sampler/iterable __iter__ path would silently DISCARD the
        # restored position otherwise
        gen2 = ({"input_ids": np.zeros((2, 4), np.int64)} for _ in range(3))
        lo2 = DeepSpeedTpuDataLoader(gen2, batch_size=2)
        good_sd = self._loader().state_dict()
        with pytest.raises(NotImplementedError):
            lo2.load_state_dict(good_sd)


# ----------------------------------------------------------- resume parity
class TestResumeParity:
    def test_crash_auto_resume_byte_parity(self, tmp_path, reference):
        engine = build_engine(tmp_path, faults={
            "enabled": True,
            "schedule": [{"kind": "crash", "at_step": 5}]})
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "completed"
        assert r["train_restarts"] == 1
        # checkpoint every 2 steps, killed at 5 → exactly step 5 lost
        assert r["steps_lost"] == 1
        assert r["restart_log"][0]["resumed_step"] == 4
        assert sup.losses_by_step() == reference["losses"]
        assert_same_params(reference["params"], params_of(engine))

    def test_sigterm_urgent_save_then_resume_parity(self, tmp_path,
                                                    reference):
        engine = build_engine(tmp_path, faults={
            "enabled": True,
            "schedule": [{"kind": "sigterm", "at_step": 5}]})
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "preempted"
        assert r["completed_steps"] == 5        # stopped AT the notice
        assert r["preemptions"] == 1
        # the urgent save ran, was measured, and beat the grace window
        assert r["urgent_save_s"] is not None
        assert r["urgent_save_s"] < sup.config.preempt_grace_s
        assert (tmp_path / "latest").read_text().strip() == "global_step5"
        # "restart after preemption": a fresh engine + supervisor over the
        # same save_dir resumes from 'latest' — zero steps lost
        engine2 = build_engine(tmp_path)
        sup2 = TrainingSupervisor(engine=engine2)
        r2 = sup2.run(N_STEPS)
        assert r2["status"] == "completed" and r2["steps_lost"] == 0
        merged = dict(sup.losses_by_step())
        merged.update(sup2.losses_by_step())
        assert merged == reference["losses"]
        assert_same_params(reference["params"], params_of(engine2))

    def test_same_supervisor_resumes_after_preemption(self, tmp_path,
                                                      reference):
        """The docstring contract: calling run() AGAIN on the same
        instance after a preemption IS the resume path (the honored
        preempt flag must not poison the next run)."""
        engine = build_engine(tmp_path, faults={
            "enabled": True,
            "schedule": [{"kind": "sigterm", "at_step": 5}]})
        sup = TrainingSupervisor(engine=engine)
        assert sup.run(N_STEPS)["status"] == "preempted"
        r2 = sup.run(N_STEPS)
        assert r2["status"] == "completed"
        assert r2["completed_steps"] == N_STEPS
        assert sup.losses_by_step() == reference["losses"]
        assert_same_params(reference["params"], params_of(engine))

    def test_real_sigterm_signal_path(self, tmp_path):
        """The injector delivers a REAL SIGTERM through the installed
        handler when run() owns the main thread — the production signal
        machinery, not just the internal flag."""
        import threading
        assert threading.current_thread() is threading.main_thread()
        engine = build_engine(tmp_path, faults={
            "enabled": True,
            "schedule": [{"kind": "sigterm", "at_step": 3}]})
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert sup._signal_installed is False      # handler restored
        assert r["status"] == "preempted" and r["completed_steps"] == 3
        assert [e[0] for e in sup.injector.fired_events()] == ["sigterm"]

    def test_mid_epoch_dataloader_resume(self, tmp_path):
        """Crash mid-epoch: the dataloader position (not just the epoch)
        must restore, or the resumed run re-reads batches and the loss
        curve silently forks."""
        # 7 batches/epoch at gas=2 → steps straddle epoch boundaries and
        # checkpoints land mid-epoch
        data = tiny_data(n=224, seq=32)
        d_ref = tmp_path / "ref"
        e_ref = build_engine(d_ref, data=data)
        sup_ref = TrainingSupervisor(engine=e_ref)
        sup_ref.run(N_STEPS)
        d = tmp_path / "chaos"
        engine = build_engine(d, data=data, faults={
            "enabled": True,
            "schedule": [{"kind": "crash", "at_step": 5}]})
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "completed"
        st = engine.training_dataloader.state_dict()
        assert 0 < st["batches_yielded"] < 7    # genuinely mid-epoch
        assert sup.losses_by_step() == sup_ref.losses_by_step()
        assert_same_params(params_of(e_ref), params_of(engine))

    def test_crash_after_skipped_step_keeps_parity(self, tmp_path):
        """The host step counter counts overflow-SKIPPED steps the device
        counter excludes; both must round-trip the manifest or a resume
        after any skipped step replays one extra step and the loss curve
        forks (the two runs here share the same single nan injection, so
        their trajectories are comparable)."""
        skip_fault = {"kind": "nan_grads", "at_step": 3, "count": 1}
        d_ref = tmp_path / "ref"
        e_ref = build_engine(d_ref, faults={
            "enabled": True, "schedule": [dict(skip_fault)]},
            max_consecutive_anomalies=5)
        sup_ref = TrainingSupervisor(engine=e_ref)
        r_ref = sup_ref.run(N_STEPS)
        assert r_ref["status"] == "completed" and r_ref["train_restarts"] == 0
        assert int(e_ref.skipped_steps) == 1

        d = tmp_path / "chaos"
        engine = build_engine(d, faults={
            "enabled": True,
            "schedule": [dict(skip_fault),
                         {"kind": "crash", "at_step": 6}]},
            max_consecutive_anomalies=5)
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "completed" and r["train_restarts"] == 1
        # restored host counter includes the skipped step: resume replays
        # from the save at host-step 6, not one step earlier
        assert r["restart_log"][0]["resumed_step"] == 6
        assert sup.losses_by_step() == sup_ref.losses_by_step()
        assert_same_params(params_of(e_ref), params_of(engine))

    def test_injectors_off_byte_identical(self, tmp_path, reference):
        """All injectors off (and supervision on) = byte-identical
        training behavior vs the plain train loop."""
        engine = build_engine(tmp_path)
        plain = {}
        while engine.global_steps < N_STEPS:
            loss = float(engine.train_batch())
            plain[engine.global_steps] = loss
        assert plain == reference["losses"]
        assert_same_params(reference["params"], params_of(engine))


# -------------------------------------------------------- watchdog + wedge
class TestWedgeRecovery:
    def test_watchdog_detects_wedge_dumps_and_restarts(self, tmp_path):
        """Acceptance: the watchdog detects an injected wedged step,
        dumps the flight recorder, and the supervisor restarts from
        'latest' without human intervention."""
        def factory():
            return build_engine(
                tmp_path,
                faults={"enabled": True, "schedule": [
                    {"kind": "slow_step", "at_step": 5,
                     "duration_s": 30.0}]},
                watchdog_enabled=True, watchdog_factor=8.0,
                watchdog_min_steps=3, watchdog_poll_s=0.05)

        sup = TrainingSupervisor(engine_factory=factory)
        r = sup.run(N_STEPS)
        assert r["status"] == "completed"
        assert r["wedges"] == 1 and r["train_restarts"] == 1
        assert r["restart_log"][0]["reason"] == "wedge"
        # checkpointed at step 4, wedged at 5 → resumed at 4
        assert r["restart_log"][0]["resumed_step"] == 4
        assert r["dump_paths"], "wedge must dump the flight recorder"
        for paths in r["dump_paths"]:
            assert os.path.exists(paths["json"])
            assert os.path.exists(paths["chrome_trace"])

    def test_wedge_without_factory_parks(self, tmp_path):
        engine = build_engine(
            tmp_path,
            faults={"enabled": True, "schedule": [
                {"kind": "slow_step", "at_step": 3, "duration_s": 30.0}]},
            watchdog_enabled=True, watchdog_factor=8.0,
            watchdog_min_steps=2, watchdog_poll_s=0.05)
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        # the stuck thread owns the engine; no factory → no safe restart
        assert r["status"] == "parked" and r["parked"]
        assert r["wedges"] == 1


# ------------------------------------------------------- anomaly rollback
class TestAnomalyRollback:
    def test_nan_grads_skip_then_rollback(self, tmp_path):
        """One poisoned step is absorbed by the engine's overflow gate
        (bounded step-skip, every precision); K consecutive trigger a
        rollback to the last good checkpoint and training completes."""
        engine = build_engine(
            tmp_path,
            faults={"enabled": True, "schedule": [
                {"kind": "nan_grads", "at_step": 4, "count": 3}]},
            max_consecutive_anomalies=2)
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "completed"
        assert r["anomaly_rollbacks"] == 1
        assert r["train_restarts"] == 1
        # poisoned steps were SKIPPED by the update (params never saw NaN)
        assert int(sup.engine.skipped_steps) >= 1
        final = sup.losses_by_step()[N_STEPS]
        assert np.isfinite(final)
        for leaf in params_of(sup.engine):
            assert np.isfinite(leaf).all()

    def test_single_anomaly_does_not_roll_back(self, tmp_path):
        engine = build_engine(
            tmp_path,
            faults={"enabled": True, "schedule": [
                {"kind": "nan_grads", "at_step": 4, "count": 1}]},
            max_consecutive_anomalies=3)
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "completed"
        assert r["anomaly_rollbacks"] == 0 and r["train_restarts"] == 0
        assert int(sup.engine.skipped_steps) == 1

    def test_parked_anomaly_storm_counts_no_rollback(self, tmp_path):
        """An anomaly storm with no checkpoint and no factory parks —
        and must NOT report a rollback that never happened (the gauge
        operators alert on)."""
        engine = build_engine(
            tmp_path,
            faults={"enabled": True, "schedule": [
                {"kind": "nan_grads", "at_step": 0, "count": 0}]},
            max_consecutive_anomalies=2, save_interval_steps=0)
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "parked"
        assert r["anomaly_rollbacks"] == 0 and r["train_restarts"] == 0

    def test_preempt_mid_streak_skips_urgent_save(self, tmp_path):
        """A SIGTERM landing inside an open anomaly streak must NOT
        publish the anomalous state as 'latest': the urgent save is
        skipped (logged), and 'latest' keeps naming the last GOOD
        checkpoint — otherwise a later rollback would restore the spiked
        params permanently."""
        engine = build_engine(
            tmp_path,
            faults={"enabled": True, "schedule": [
                {"kind": "nan_grads", "at_step": 4, "count": 1},
                {"kind": "sigterm", "at_step": 5}]},
            max_consecutive_anomalies=5)
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "preempted"
        assert r["preemptions"] == 1
        assert r["urgent_save_s"] is None          # no urgent save ran
        # the poisoned step completed as global_step 5 (anomalous, odd —
        # never a periodic save); the urgent tag it would have written
        # must not exist and 'latest' still names the last good save
        assert not (tmp_path / "global_step5").exists()
        assert (tmp_path / "latest").read_text().strip() == "global_step4"

    def test_loss_spike_detection(self):
        from collections import deque
        cfg = ResilienceConfig(enabled=True, save_dir="/tmp/x",
                               loss_spike_factor=5.0)
        sup = TrainingSupervisor.__new__(TrainingSupervisor)
        sup.config = cfg

        class FakeEngine:
            _last_metrics = {"overflow": np.asarray(False)}

        good = deque([2.0, 2.1, 1.9], maxlen=10)
        assert not sup._is_anomaly(FakeEngine(), 2.5, good)
        assert sup._is_anomaly(FakeEngine(), 50.0, good)
        assert sup._is_anomaly(FakeEngine(), float("nan"), good)
        FakeEngine._last_metrics = {"overflow": np.asarray(True)}
        assert sup._is_anomaly(FakeEngine(), 2.0, good)


# --------------------------------------------------------- circuit breaker
class TestCircuitBreaker:
    def test_persistent_crash_parks(self, tmp_path):
        def factory():
            return build_engine(tmp_path, faults={
                "enabled": True,
                "schedule": [{"kind": "crash", "at_step": 2, "count": 0}]},
                max_restarts_in_window=3)

        sup = TrainingSupervisor(engine_factory=factory)
        r = sup.run(N_STEPS)
        assert r["status"] == "parked" and r["parked"]
        assert r["train_restarts"] == 2     # 3rd failure trips the breaker

    def test_crash_before_any_checkpoint_needs_factory(self, tmp_path):
        engine = build_engine(tmp_path, faults={
            "enabled": True,
            "schedule": [{"kind": "crash", "at_step": 1}]},
            save_interval_steps=0)
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "parked"      # no checkpoint, no factory


# ----------------------------------------------------------- config surface
class TestConfigSurface:
    def test_resilience_block_mounts_on_ds_config(self):
        from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig
        cfg = DeepSpeedTpuConfig(resilience={
            "enabled": True, "save_dir": "/ckpt",
            "save_interval_steps": 50,
            "faults": {"enabled": True, "seed": 3, "schedule": [
                {"kind": "sigterm", "at_step": 100}]}})
        assert cfg.resilience.enabled and cfg.resilience.save_dir == "/ckpt"
        inj = cfg.resilience.faults.build_injector()
        assert inj.events[0].kind == "sigterm"
        # default = everything off
        assert not DeepSpeedTpuConfig().resilience.enabled

    def test_disabled_supervisor_refuses_to_run(self, tmp_path):
        engine = build_engine(tmp_path)
        engine.config.resilience.enabled = False
        sup = TrainingSupervisor(engine=engine,
                                 config=engine.config.resilience,
                                 save_dir=str(tmp_path))
        with pytest.raises(ValueError, match="resilience.enabled"):
            sup.run(2)

    def test_supervisor_requires_save_dir(self, tmp_path):
        engine = build_engine(tmp_path)
        engine.config.resilience.save_dir = None
        with pytest.raises(ValueError, match="save_dir"):
            TrainingSupervisor(engine=engine)


# ------------------------------------------- LR + ScaleState resume exactness
class TestScheduleAndScaleResume:
    def test_warmup_lr_continues_without_rewarmup(self, tmp_path,
                                                  reference):
        """The LR schedule is serialized in the manifest and keyed off the
        restored global_step: after resume the very next step's LR equals
        the uninterrupted run's — no re-warmup from step 0."""
        engine = build_engine(tmp_path, faults={
            "enabled": True,
            "schedule": [{"kind": "crash", "at_step": 5}]})
        sup = TrainingSupervisor(engine=engine)
        r = sup.run(N_STEPS)
        assert r["status"] == "completed", r
        ref_engine = build_engine(tmp_path / "ref2")
        r_ref = TrainingSupervisor(engine=ref_engine).run(N_STEPS)
        assert r_ref["status"] == "completed", r_ref
        assert engine.get_lr() == ref_engine.get_lr()
        assert engine.lr_scheduler.state_dict() == \
            ref_engine.lr_scheduler.state_dict()

    def test_fp16_scale_state_continues_exactly(self, tmp_path):
        """ScaleState (scale, good-step window, hysteresis) round-trips
        the manifest: a resumed fp16 run continues the loss-scale window
        instead of resetting to the initial scale."""
        topo.reset_topology()
        cfg = make_config(tmp_path, save_interval_steps=0)
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8,
                       "loss_scale_window": 4}
        del cfg["resilience"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_model("tiny"), config=cfg,
            training_data=tiny_data())
        for _ in range(6):
            engine.train_batch()
        engine.save_checkpoint(str(tmp_path), client_state={
            "dataloader": engine.training_dataloader.state_dict()})
        want = (float(engine.state.scale_state.scale),
                int(engine.state.scale_state.good_steps),
                int(engine.state.scale_state.hysteresis))
        # the window moved off the initial state, so a reset would show
        assert want[1] != 0 or want[0] != 2.0 ** 8

        topo.reset_topology()
        engine2, _, _, _ = deepspeed_tpu.initialize(
            model=build_model("tiny"), config=dict(cfg),
            training_data=tiny_data())
        _, cs = engine2.load_checkpoint(str(tmp_path))
        engine2.training_dataloader.load_state_dict(cs["dataloader"])
        engine2.reset_data_iterator()
        got = (float(engine2.state.scale_state.scale),
               int(engine2.state.scale_state.good_steps),
               int(engine2.state.scale_state.hysteresis))
        assert got == want
        # and both engines continue with the same scale trajectory
        l1 = float(engine.train_batch())
        l2 = float(engine2.train_batch())
        assert float(engine.state.scale_state.scale) == \
            float(engine2.state.scale_state.scale)
        assert l1 == l2
