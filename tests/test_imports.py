"""Every subsystem module imports cleanly — the component inventory's
cheapest regression guard (catches import cycles introduced by lazy-import
refactors)."""

import importlib

import pytest

MODULES = [
    "deepspeed_tpu",
    "deepspeed_tpu.accelerator",
    "deepspeed_tpu.autotuning.autotuner",
    "deepspeed_tpu.comm.comm",
    "deepspeed_tpu.compression",
    "deepspeed_tpu.elasticity",
    "deepspeed_tpu.env_report",
    "deepspeed_tpu.inference.engine",
    "deepspeed_tpu.inference.quantization",
    "deepspeed_tpu.inference.v2.engine_v2",
    "deepspeed_tpu.inference.v2.kv_quant",
    "deepspeed_tpu.inference.v2.kv_tier",
    "deepspeed_tpu.inference.v2.paged_model",
    "deepspeed_tpu.inference.v2.weight_quant",
    "deepspeed_tpu.inference.v2.ragged.blocked_allocator",
    "deepspeed_tpu.inference.v2.ragged.manager",
    "deepspeed_tpu.inference.v2.scheduler",
    "deepspeed_tpu.launcher.runner",
    "deepspeed_tpu.models",
    "deepspeed_tpu.models.convert",
    "deepspeed_tpu.moe.grouped",
    "deepspeed_tpu.moe.sharded_moe",
    "deepspeed_tpu.monitor.monitor",
    "deepspeed_tpu.ops",
    "deepspeed_tpu.ops.evoformer_attn",
    "deepspeed_tpu.ops.flash_attention",
    "deepspeed_tpu.ops.onebit",
    "deepspeed_tpu.ops.paged_attention",
    "deepspeed_tpu.ops.quantizer",
    "deepspeed_tpu.ops.sparse_attention",
    "deepspeed_tpu.ops.spatial",
    "deepspeed_tpu.parallel.pipeline",
    "deepspeed_tpu.parallel.sharding",
    "deepspeed_tpu.parallel.zeropp",
    "deepspeed_tpu.profiling",
    "deepspeed_tpu.runtime.activation_checkpointing",
    "deepspeed_tpu.runtime.checkpointing",
    "deepspeed_tpu.runtime.data_pipeline",
    "deepspeed_tpu.runtime.dataloader",
    "deepspeed_tpu.runtime.engine",
    "deepspeed_tpu.runtime.resilience",
    "deepspeed_tpu.runtime.hybrid_engine",
    "deepspeed_tpu.runtime.pipe",
    "deepspeed_tpu.runtime.zero_infinity",
    "deepspeed_tpu.runtime.zero_offload",
    "deepspeed_tpu.sequence.layer",
    "deepspeed_tpu.sequence.ring_attention",
    "deepspeed_tpu.serving",
    "deepspeed_tpu.serving.config",
    "deepspeed_tpu.serving.faults",
    "deepspeed_tpu.serving.frontend",
    "deepspeed_tpu.serving.handoff",
    "deepspeed_tpu.serving.queue",
    "deepspeed_tpu.serving.replica",
    "deepspeed_tpu.serving.router",
    "deepspeed_tpu.serving.supervisor",
    "deepspeed_tpu.telemetry",
    "deepspeed_tpu.telemetry.flight_recorder",
    "deepspeed_tpu.telemetry.journal",
    "deepspeed_tpu.telemetry.slo",
    "deepspeed_tpu.telemetry.windowed",
    "deepspeed_tpu.utils.comms_logging",
    "deepspeed_tpu.utils.restart",
    "deepspeed_tpu.utils.zero_to_fp32",
]


@pytest.mark.parametrize("mod", MODULES)
def test_module_imports(mod):
    importlib.import_module(mod)
