"""Unified telemetry (ISSUE 4): tracer core, chrome-trace export, flight
recorder, request-trace chain through the serving stack, step profiling in
the training engine, and the observability satellites (MonitorMaster
per-backend isolation, Prometheus exposition, ThroughputTimer memory)."""

import json
import logging
import threading
import time
import tracemalloc

import numpy as np
import pytest

from deepspeed_tpu.telemetry import (NOOP_SPAN, NOOP_TRACER, FlightRecorder,
                                     TelemetryConfig, Tracer, chrome_trace,
                                     trace_coverage, validate_chrome_trace)

VOCAB = 128


def tiny_engine(max_seqs=4, **cfg_over):
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=2,
                            max_seq_len=128, norm="rmsnorm",
                            activation="silu", position="rope")
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
        max_tracked_sequences=16, **cfg_over)
    return InferenceEngineV2(CausalLM(cfg), config=vcfg)


# ------------------------------------------------------------- tracer core
def test_span_nesting_and_ordering():
    tr = Tracer()
    with tr.span("outer", trace_id="t") as outer:
        time.sleep(0.001)
        with tr.span("inner") as inner:
            assert tr.current() is inner
            time.sleep(0.001)
        assert tr.current() is outer
    assert tr.current() is None
    spans = tr.export()
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["trace_id"] == "t"    # inherited from parent
    # completion order: inner ends first; interval containment holds
    assert spans[0]["name"] == "inner"
    assert by_name["outer"]["t_start"] <= by_name["inner"]["t_start"]
    assert by_name["inner"]["t_end"] <= by_name["outer"]["t_end"]


def test_begin_end_cross_thread():
    tr = Tracer()
    sp = tr.begin("xthread", trace_id="req-1", attrs={"k": 1})

    t = threading.Thread(target=sp.end)
    t.start()
    t.join()
    (d,) = tr.export()
    assert d["name"] == "xthread" and d["t_end"] is not None
    assert d["attrs"]["k"] == 1
    sp.end()                       # idempotent: no double record
    assert len(tr.export()) == 1


def test_ring_buffer_eviction():
    tr = Tracer(max_spans=4)
    for i in range(10):
        tr.begin(f"s{i}").end()
    spans = tr.export()
    assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]


def test_open_spans_visible_and_capped():
    tr = Tracer(max_spans=4)
    spans = [tr.begin(f"open{i}") for i in range(6)]
    ex = tr.export(include_open=True)
    assert all(s["t_end"] is None and s["attrs"]["open"] for s in ex)
    assert len(ex) == 4            # leak cap at max_spans
    assert tr.export(include_open=False) == []
    for sp in spans:
        sp.end()


def test_disabled_is_noop_singleton():
    tr = Tracer(enabled=False)
    assert tr.span("a") is NOOP_SPAN
    assert tr.begin("b") is NOOP_SPAN
    with tr.span("c") as sp:
        assert sp.set("k", 1) is sp
    assert tr.export() == []
    assert NOOP_TRACER.span("d") is NOOP_SPAN


def test_disabled_hot_path_allocation_free():
    """The disabled span() path must not allocate per call — the
    guarantee that lets the scheduler/engine keep tracer calls on their
    hot paths. A transient constant residual (the in-flight bound-method
    object tracemalloc catches) is tolerated; per-iteration growth over
    2000 spans is not."""
    tr = Tracer(enabled=False)
    with tr.span("warm"):          # warm any lazy state
        pass
    here = __file__
    tracer_file = Tracer.__init__.__code__.co_filename
    tracemalloc.start()
    try:
        before = tracemalloc.take_snapshot()
        for _ in range(2000):
            with tr.span("hot"):
                pass
            tr.begin("hot2").set("k", 1).end()
        after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    leaked_objects = sum(
        st.count_diff for st in after.compare_to(before, "lineno")
        if st.traceback and st.traceback[0].filename in (here, tracer_file)
        and st.count_diff > 0)
    # 2000 iterations × 2 spans would leave thousands of objects if the
    # disabled path allocated; tracemalloc catches at most a handful of
    # in-flight bound-method objects regardless of the iteration count
    assert leaked_objects <= 8, (
        f"disabled tracer leaked {leaked_objects} objects over 2000 spans")
    assert tr.export() == []


# ------------------------------------------------------------ chrome trace
def test_chrome_trace_schema_valid():
    tr = Tracer()
    with tr.span("a", trace_id="req-1", attrs={"x": 3}):
        with tr.span("b"):
            pass
    tr.begin("other", trace_id="replica-0").end()
    open_span = tr.begin("inflight", trace_id="req-1")
    obj = chrome_trace(tr.export(), meta={"reason": "test"})
    assert validate_chrome_trace(obj) == []
    # JSON round-trip stays valid (what lands on disk is what's checked)
    assert validate_chrome_trace(json.dumps(obj)) == []
    events = obj["traceEvents"]
    procs = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert {"req-1", "replica-0"} <= procs
    assert any(e["ph"] == "B" and e["name"] == "inflight" for e in events)
    xs = [e for e in events if e["ph"] == "X"]
    assert xs and all(e["dur"] >= 0 and e["ts"] >= 0 for e in xs)
    open_span.end()


def test_validate_chrome_trace_catches_garbage():
    assert validate_chrome_trace("not json{")
    assert validate_chrome_trace({"no_events": 1})
    bad = {"traceEvents": [{"name": "x", "ph": "X", "pid": 1, "tid": 1,
                            "ts": 0.0}]}          # X without dur
    assert any("dur" in p for p in validate_chrome_trace(bad))
    assert validate_chrome_trace({"traceEvents": [
        {"name": "", "ph": "Z", "pid": "a", "tid": 1, "ts": 0}]})


def test_trace_coverage():
    mk = lambda a, b: {"t_start": a, "t_end": b}  # noqa: E731
    assert trace_coverage([mk(0, 1)], 0, 1) == pytest.approx(1.0)
    # overlap is not double counted; gaps subtract
    assert trace_coverage([mk(0, 0.6), mk(0.4, 1.0)], 0, 1) \
        == pytest.approx(1.0)
    assert trace_coverage([mk(0, 0.25), mk(0.75, 1.0)], 0, 1) \
        == pytest.approx(0.5)
    # open span counts to the window end; out-of-window clipped
    assert trace_coverage([{"t_start": 0.5, "t_end": None}], 0, 1) \
        == pytest.approx(0.5)
    assert trace_coverage([], 0, 1) == 0.0


# --------------------------------------------------------- flight recorder
def test_flight_recorder_dump_and_snapshots(tmp_path):
    tr = Tracer()
    rec = FlightRecorder(tr, max_snapshots=3, dump_dir=str(tmp_path))
    rec.add_metrics_provider("m", lambda: {"v": 7})
    rec.add_metrics_provider("boom", lambda: 1 / 0)   # guarded provider
    for _ in range(5):
        rec.snapshot_metrics()
    with tr.span("work", trace_id="t"):
        pass
    paths = rec.dump(reason="unit")
    record = json.load(open(paths["json"]))
    assert record["format"].startswith("deepspeed_tpu.flight_recorder")
    assert len(record["metric_snapshots"]) == 3          # ring bounded
    assert record["metric_snapshots"][0]["m"] == {"v": 7}
    assert "error" in record["metric_snapshots"][0]["boom"]
    assert [s["name"] for s in record["spans"]] == ["work"]
    assert validate_chrome_trace(json.load(open(paths["chrome_trace"]))) == []


def test_flight_recorder_on_error_rate_limited(tmp_path):
    """Error dumps are limited per sliding window, not per lifetime —
    a burst consumes the slots, but a later incident (after the window)
    is captured again."""
    clock = [100.0]
    tr = Tracer(clock=lambda: clock[0])
    rec = FlightRecorder(tr, dump_dir=str(tmp_path), max_error_dumps=2,
                         error_dump_window_s=60.0)
    outs = [rec.on_error("replica-0", RuntimeError(f"e{i}"))
            for i in range(4)]
    assert [o is not None for o in outs] == [True, True, False, False]
    clock[0] += 61.0                   # window expires → slots free again
    assert rec.on_error("replica-0", RuntimeError("later")) is not None
    # disabled telemetry: error dumps are a no-op, not a file
    rec2 = FlightRecorder(NOOP_TRACER, dump_dir=str(tmp_path))
    assert rec2.on_error("x", RuntimeError()) is None


def test_telemetry_config_builders():
    tc = TelemetryConfig()
    assert tc.build_tracer() is NOOP_TRACER
    tc_on = TelemetryConfig(enabled=True, max_spans=16, xla_annotations=True)
    tr = tc_on.build_tracer()
    assert tr.enabled and tr.max_spans == 16 and tr.xla_annotations
    rec = tc_on.build_recorder(tr)
    assert isinstance(rec, FlightRecorder)


# ------------------------------------------------- satellites: prometheus
def test_render_prometheus_counters_gauges():
    from deepspeed_tpu.serving import MetricsRegistry

    reg = MetricsRegistry("serving")
    reg.counter("requests_completed").inc(3)
    reg.gauge("queue_depth").set(5)
    text = reg.render_prometheus()
    lines = text.splitlines()
    assert "# TYPE serving_requests_completed counter" in lines
    assert "serving_requests_completed 3" in lines
    assert "# TYPE serving_queue_depth gauge" in lines
    assert "serving_queue_depth 5" in lines
    assert text.endswith("\n")


def test_render_prometheus_histogram_buckets():
    from deepspeed_tpu.serving import MetricsRegistry

    reg = MetricsRegistry("serving")
    h = reg.histogram("lat_s", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 99.0):    # one over-range sample
        h.observe(v)
    text = reg.render_prometheus()
    lines = text.splitlines()
    assert "# TYPE serving_lat_s histogram" in lines
    # cumulative counts per le, with +Inf covering the overflow sample
    assert 'serving_lat_s_bucket{le="0.1"} 1' in lines
    assert 'serving_lat_s_bucket{le="1"} 3' in lines
    assert 'serving_lat_s_bucket{le="10"} 4' in lines
    assert 'serving_lat_s_bucket{le="+Inf"} 5' in lines
    assert "serving_lat_s_count 5" in lines
    (sum_line,) = [ln for ln in lines if ln.startswith("serving_lat_s_sum")]
    assert float(sum_line.split()[1]) == pytest.approx(105.05)


def test_percentile_clamps_to_largest_finite_bound():
    from deepspeed_tpu.serving.metrics import Histogram

    h = Histogram(buckets=(0.1, 1.0))
    for _ in range(10):
        h.observe(50.0)           # everything over-range
    for q in (1, 50, 99, 100):
        p = h.percentile(q)
        assert np.isfinite(p) and p == 1.0
    assert Histogram(buckets=()).percentile(50) == 0.0


# ---------------------------------------------- satellites: monitor master
def test_monitor_master_isolates_backend_failures(tmp_path, monkeypatch):
    from deepspeed_tpu.monitor import monitor as mon
    from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig

    class Boom(mon.Monitor):
        def __init__(self, *a, **k):
            raise RuntimeError("backend exploded")

    # an early backend failing must not take down the later ones
    monkeypatch.setattr(mon, "CSVMonitor", Boom)
    seen = []

    class Fake(mon.Monitor):
        def __init__(self, *a, **k):
            pass

        def write_events(self, events):
            seen.extend(events)

    monkeypatch.setattr(mon, "TensorBoardMonitor", Fake)
    cfg = DeepSpeedTpuConfig(
        csv_monitor={"enabled": True, "output_path": str(tmp_path)},
        tensorboard={"enabled": True, "output_path": str(tmp_path)})
    # the package logger does not propagate; attach a capture handler
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = Capture(level=logging.WARNING)
    mon.logger.addHandler(handler)
    try:
        mm = mon.MonitorMaster(cfg)
    finally:
        mon.logger.removeHandler(handler)
    assert len(mm.backends) == 1 and isinstance(mm.backends[0], Fake)
    assert any("csv_monitor" in m and "failed to initialize" in m
               for m in records)
    mm.write_events([("a/b", 1.0, 0)])
    assert seen == [("a/b", 1.0, 0)]


def test_monitor_master_all_backends_ok(tmp_path):
    from deepspeed_tpu.monitor import monitor as mon
    from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig

    cfg = DeepSpeedTpuConfig(
        csv_monitor={"enabled": True, "output_path": str(tmp_path),
                     "job_name": "j"})
    mm = mon.MonitorMaster(cfg)
    assert len(mm.backends) == 1
    mm.write_events([("Train/loss", 1.5, 3)])
    out = tmp_path / "j" / "Train_loss.csv"
    assert out.exists() and "1.5" in out.read_text()


# ------------------------------------------ satellites: throughput memory
def test_throughput_timer_monitor_memory():
    import jax.numpy as jnp

    from deepspeed_tpu.utils.timer import ThroughputTimer

    keepalive = jnp.ones((256, 256))      # ensure live device bytes exist
    logs = []
    t = ThroughputTimer(batch_size=4, start_step=1, steps_per_output=1,
                        monitor_memory=True, logging_fn=logs.append)
    for _ in range(2):
        t.start()
        time.sleep(0.001)
        t.stop()
    assert t.memory_bytes is not None
    assert t.memory_bytes >= keepalive.nbytes
    assert any("device_mem=" in m for m in logs)
    # off by default: no memory sampling, no log decoration
    t2 = ThroughputTimer(batch_size=4, start_step=1, steps_per_output=1,
                         logging_fn=logs.append)
    t2.start()
    t2.stop()
    assert t2.memory_bytes is None
    del keepalive


# ----------------------------------------------- engine step profiling
@pytest.mark.parametrize("via", ["wall_clock_breakdown", "telemetry"])
def test_engine_step_profiling(via):
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
    from deepspeed_tpu.utils.timer import (FORWARD_MICRO_TIMER,
                                           STEP_GLOBAL_TIMER)

    cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                            intermediate_size=64, num_layers=2, num_heads=2,
                            max_seq_len=64, norm="rmsnorm",
                            activation="silu", position="rope")
    ds = {"train_micro_batch_size_per_gpu": 2,
          "gradient_accumulation_steps": 2,
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "steps_per_print": 1, "mesh": {"data": -1, "fsdp": 1}}
    ds[via] = {"enabled": True} if via == "telemetry" else True
    engine, _, _, _ = deepspeed_tpu.initialize(model=CausalLM(cfg),
                                               config=ds)
    rng = np.random.default_rng(0)
    gb = 2 * engine.topology.get_data_parallel_world_size()
    data = {"input_ids": rng.integers(0, 64, size=(gb, 33), dtype=np.int64)}
    engine.train_batch(iter([data, data]))
    # flops_per_sample auto-populated from the flops profiler (satellite)
    from deepspeed_tpu.profiling import train_step_flops

    assert engine.tput_timer.flops_per_sample \
        == pytest.approx(train_step_flops(cfg, 1, 32))
    # synchronized timers recorded both phases
    assert engine.timers.has(FORWARD_MICRO_TIMER)
    assert engine.timers.has(STEP_GLOBAL_TIMER)
    assert engine.timers(FORWARD_MICRO_TIMER).mean() > 0
    if via == "telemetry":
        names = [s["name"] for s in engine.tracer.export()]
        assert names.count("fwd_bwd") == 2       # gas=2 micro steps
        assert names.count("optimizer_step") == 1
        assert all(s["trace_id"] == "train" for s in engine.tracer.export())
    else:
        assert not engine.tracer.enabled


def test_engine_profiling_off_by_default():
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, hidden_size=32,
                            intermediate_size=64, num_layers=2, num_heads=2,
                            max_seq_len=64, norm="rmsnorm",
                            activation="silu", position="rope")
    ds = {"train_micro_batch_size_per_gpu": 2,
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "steps_per_print": 10**9, "mesh": {"data": -1, "fsdp": 1}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=CausalLM(cfg),
                                               config=ds)
    rng = np.random.default_rng(0)
    gb = 2 * engine.topology.get_data_parallel_world_size()
    data = {"input_ids": rng.integers(0, 64, size=(gb, 17), dtype=np.int64)}
    engine.train_batch(iter([data]))
    assert not engine._profile_steps
    assert engine.tracer is NOOP_TRACER
    assert not engine.timers.timers       # no timers touched off the path


# --------------------------------------------------- end-to-end serving
def _stage_spans(spans, trace_id):
    return {s["name"]: s for s in spans if s["trace_id"] == trace_id}


def test_e2e_request_span_chain():
    """An end-to-end serving request produces the complete
    queue→route→admit→prefill→decode chain under one trace id, with
    prefix-cache and speculation attributes, covering ≥95% of TTFT."""
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend

    eng = tiny_engine(enable_prefix_cache=True)
    fe = ServingFrontend([eng], ServingConfig(
        max_queue_depth=8,
        speculative={"enabled": True, "mode": "ngram", "ngram_max": 3,
                     "max_draft_tokens": 3},
        telemetry={"enabled": True}))
    try:
        rng = np.random.default_rng(0)
        motif = rng.integers(0, VOCAB, size=8).tolist()
        prompt = motif * 4                       # 32 tokens, 4 full blocks
        h1 = fe.submit(prompt, max_new_tokens=6)
        assert fe.wait_all([h1], timeout=120)
        # same prompt again: the prefix cache now has blocks to hit
        h2 = fe.submit(prompt, max_new_tokens=6)
        assert fe.wait_all([h2], timeout=120)

        spans = fe.tracer.export()
        for h in (h1, h2):
            req = h._req
            chain = _stage_spans(spans, req.trace_id)
            assert {"request", "queue", "route", "admit", "prefill",
                    "decode"} <= set(chain)
            # stage ordering: each stage starts no earlier than the last
            order = ["queue", "route", "admit", "prefill", "decode"]
            for a, b in zip(order, order[1:]):
                assert chain[a]["t_start"] <= chain[b]["t_start"] + 1e-9
                assert chain[a]["t_end"] <= chain[b]["t_end"] + 1e-9
            root = chain["request"]
            assert root["attrs"]["state"] == "finished"
            assert root["attrs"]["finish_reason"] == "length"
            assert root["attrs"]["generated"] == 6
            # TTFT coverage ≥ 95% (the acceptance criterion, in-test)
            stages = [chain[n] for n in order[:-1]]
            cov = trace_coverage(stages, req.arrival_t, req.first_token_t)
            assert cov >= 0.95, f"span chain covers only {cov:.1%} of TTFT"
            # speculation attrs live on the decode span (repetitive
            # prompt → the n-gram proposer must have proposed)
            assert chain["decode"]["attrs"].get("spec_proposed", 0) > 0
        # prefix attrs: first request misses, second hits full blocks
        c1 = _stage_spans(spans, h1._req.trace_id)["prefill"]["attrs"]
        c2 = _stage_spans(spans, h2._req.trace_id)["prefill"]["attrs"]
        assert c1["prefix_matched_tokens"] == 0
        assert c2["prefix_matched_tokens"] > 0
        # per-forward spans recorded under the replica trace
        fwd = [s for s in spans if s["trace_id"] == "replica-0"
               and s["name"] == "forward"]
        assert fwd and all(s["attrs"]["n_seqs"] >= 1 for s in fwd)
        assert any(s["name"] == "spec_verify" for s in spans
                   if s["trace_id"] == "replica-0")
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_serving_telemetry_off_records_nothing():
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend

    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=8))
    try:
        rng = np.random.default_rng(1)
        h = fe.submit(rng.integers(0, VOCAB, size=12).tolist(),
                      max_new_tokens=3)
        assert fe.wait_all([h], timeout=120)
        assert not fe.tracer.enabled
        assert fe.tracer.export() == []
        assert h._req.spans is None and h._req.trace_id is None
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_greedy_parity_telemetry_on_vs_off():
    """Tracing must never change the token streams (prefix cache +
    speculation active) — byte-identical on vs off."""
    from deepspeed_tpu.inference.v2.scheduler import (
        ContinuousBatchingScheduler)
    from deepspeed_tpu.inference.v2.spec import NGramProposer
    from deepspeed_tpu.inference.v2.testing import (assert_greedy_parity,
                                                    greedy_generate)

    rng = np.random.default_rng(2)
    motif = rng.integers(0, VOCAB, size=6).tolist()
    prompts = [motif * 3 + rng.integers(0, VOCAB, size=4).tolist()
               for _ in range(3)]

    def run(tracer):
        eng = tiny_engine(enable_prefix_cache=True)
        sched = ContinuousBatchingScheduler(
            eng, proposer=NGramProposer(ngram_max=3), max_draft_tokens=3,
            tracer=tracer, trace_label="parity")
        return greedy_generate(prompts=prompts, uid_base=500,
                               max_new_tokens=8, scheduler=sched)

    ref = run(None)
    traced = run(Tracer())
    assert_greedy_parity(ref, traced, label="telemetry")


def test_frontend_debug_dump_and_prometheus(tmp_path):
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend

    fe = ServingFrontend([tiny_engine()], ServingConfig(
        max_queue_depth=8,
        telemetry={"enabled": True, "dump_dir": str(tmp_path)}))
    try:
        rng = np.random.default_rng(3)
        h = fe.submit(rng.integers(0, VOCAB, size=10).tolist(),
                      max_new_tokens=3)
        assert fe.wait_all([h], timeout=120)
        paths = fe.debug_dump()
        record = json.load(open(paths["json"]))
        assert record["reason"] == "debug"
        assert any(s["name"] == "request" for s in record["spans"])
        assert record["metric_snapshots"], "debug dump must snapshot metrics"
        snap = record["metric_snapshots"][-1]["serving"]
        assert snap["requests_completed"] == 1
        assert validate_chrome_trace(
            json.load(open(paths["chrome_trace"]))) == []
        # Prometheus rendering of the same registry, via the frontend
        text = fe.render_prometheus()
        assert "serving_requests_completed 1" in text.splitlines()
        assert 'serving_ttft_s_bucket{le="+Inf"} 1' in text.splitlines()
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_replica_error_writes_flight_record(tmp_path):
    """A replica death (engine fault) leaves a flight-recorder dump with
    the in-flight span evidence."""
    from deepspeed_tpu.serving import ServingConfig, ServingFrontend

    eng = tiny_engine()
    fe = ServingFrontend([eng], ServingConfig(
        max_queue_depth=8,
        telemetry={"enabled": True, "dump_dir": str(tmp_path)}))
    try:
        def boom(*a, **k):
            raise RuntimeError("engine exploded")

        eng.put = boom
        rng = np.random.default_rng(4)
        h = fe.submit(rng.integers(0, VOCAB, size=10).tolist(),
                      max_new_tokens=3)
        assert h._req.wait(60)
        assert h._req.state.value == "failed"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            dumps = list(tmp_path.glob("flightrec_*_error_replica-0_*.json"))
            if dumps:
                break
            time.sleep(0.05)
        assert dumps, "no flight-recorder dump after replica death"
        record = json.load(open(dumps[0]))
        assert record["reason"] == "error_replica-0"
        # the doomed request's spans are in the record (open or closed)
        assert any(s["trace_id"] == h._req.trace_id
                   for s in record["spans"])
    finally:
        fe.shutdown(drain=False, timeout=5)
