"""Ops-journal-under-churn chaos suite (ISSUE 9 satellite).

Drives the serving FaultInjector and the TrainFaultInjector through
real recovery stories and asserts the unified journal's contract held
under the churn: every restart / rollback / handoff appears EXACTLY
once (cross-checked against the supervisors' own logs and the metrics
counters — the journal must neither drop nor duplicate), the whole
stream passes schema validation, the ring stays bounded, and
timestamps are monotonic (docs/OBSERVABILITY.md "The ops event
journal").
"""

import time

import numpy as np

from deepspeed_tpu.inference.v2.engine_v2 import (
    InferenceEngineV2, RaggedInferenceEngineConfig)
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving import ServingConfig, ServingFrontend
from deepspeed_tpu.telemetry import validate_events

VOCAB = 128

_model = None
_params = None


def tiny_engine(i=0):
    global _model, _params
    if _model is None:
        _model = CausalLM(TransformerConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, max_seq_len=128, norm="rmsnorm",
            activation="silu", position="rope"))
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=4,
        max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
        max_tracked_sequences=16)
    eng = InferenceEngineV2(_model, params=_params, config=vcfg)
    _params = eng.params
    return eng


def prompts(n, seed, lo=8, hi=20):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(l)).tolist()
            for l in rng.integers(lo, hi, size=n)]


def _journal_invariants(journal):
    evs = journal.events()
    assert validate_events(evs) == [], validate_events(evs)[:5]
    assert len(journal) <= journal.capacity
    ts = [e["t"] for e in evs]
    assert ts == sorted(ts)
    return evs


def test_serving_crash_churn_journal_exact_once():
    """Replica 0 crashes mid-burst under supervision: the journal's
    replica_restart events must match the supervisor's restart log 1:1
    and request_failover events must match the counter — exactly once
    each, valid schema, monotonic, bounded."""
    scfg = ServingConfig(
        max_queue_depth=64,
        fault_tolerance={"enabled": True, "max_retries": 3,
                         "restart_backoff_s": 0.05,
                         "restart_backoff_max_s": 0.2,
                         "supervisor_poll_s": 0.02,
                         "restart_window_s": 60.0,
                         "max_restarts_in_window": 5},
        faults={"enabled": True, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 3}]})
    fe = ServingFrontend([tiny_engine(0), tiny_engine(1)], scfg,
                         engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=5) for p in prompts(8, 1)]
        assert fe.wait_all(hs, timeout=300)
        deadline = time.monotonic() + 60
        while not fe.supervisor.restart_log and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        snap = fe.metrics_snapshot()
        evs = _journal_invariants(fe.journal)
        restarts = [e for e in evs if e["kind"] == "replica_restart"]
        assert len(restarts) == len(fe.supervisor.restart_log) >= 1
        # 1:1 against the supervisor's own record, field for field
        for ev, log in zip(restarts, fe.supervisor.restart_log):
            assert ev["detail"]["replica"] == log["replica"]
            assert ev["detail"]["attempt"] == log["attempt"]
        failovers = [e for e in evs if e["kind"] == "request_failover"]
        assert len(failovers) == int(snap["requests_failed_over"])
        # one journal entry per failover uid+attempt — no duplicates
        keys = [(e["detail"]["uid"], e["detail"]["attempt"])
                for e in failovers]
        assert len(keys) == len(set(keys))
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_serving_park_and_brownout_journaled():
    """A replica that crashes on every step trips the circuit breaker:
    the park lands in the journal exactly once; with a brownout
    threshold, the capacity collapse also journals the brownout entry."""
    scfg = ServingConfig(
        max_queue_depth=16,
        fault_tolerance={"enabled": True, "restart_backoff_s": 0.01,
                         "restart_backoff_max_s": 0.05,
                         "restart_backoff_jitter": 0.0,
                         "supervisor_poll_s": 0.01,
                         "max_restarts_in_window": 2,
                         "restart_window_s": 60.0,
                         "brownout_threshold": 0.75},
        faults={"enabled": True, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 0, "count": 0}]})
    fe = ServingFrontend([tiny_engine(0), tiny_engine(1)], scfg,
                         engine_factory=tiny_engine)
    try:
        hs = []
        for p in prompts(6, 2):
            try:
                hs.append(fe.submit(p, max_new_tokens=4))
            except Exception:
                pass
        deadline = time.monotonic() + 60
        while fe.supervisor.parked_count() == 0 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert fe.supervisor.parked_count() == 1
        # survivors finish the work
        fe.wait_all(hs, timeout=120)
        evs = _journal_invariants(fe.journal)
        parks = [e for e in evs if e["kind"] == "replica_parked"]
        assert len(parks) == 1
        assert parks[0]["detail"]["replica"] == 0
        # every restart that happened before the park is journaled too
        n_restarts = len([e for e in evs
                          if e["kind"] == "replica_restart"])
        assert n_restarts == len(fe.supervisor.restart_log)
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_disagg_handoff_churn_journal_matches_counters():
    """Role-split fleet: every staged handoff journals handoff_staged
    exactly once (== handoffs_started counter); fallbacks (if any)
    journal too (== handoff_fallbacks counter)."""
    scfg = ServingConfig(
        max_queue_depth=64,
        disaggregation={"enabled": True,
                        "roles": ["prefill", "decode"],
                        "handoff": {"enabled": True, "max_staged": 8}})
    fe = ServingFrontend([tiny_engine(0), tiny_engine(1)], scfg)
    try:
        hs = [fe.submit(p, max_new_tokens=4) for p in prompts(6, 4)]
        assert fe.wait_all(hs, timeout=300)
        snap = fe.metrics_snapshot()
        assert snap["handoffs_started"] >= 1
        evs = _journal_invariants(fe.journal)
        staged = [e for e in evs if e["kind"] == "handoff_staged"]
        assert len(staged) == int(snap["handoffs_started"])
        uids = [e["detail"]["uid"] for e in staged]
        assert len(uids) == len(set(uids))
        fallbacks = [e for e in evs if e["kind"] == "handoff_fallback"]
        assert len(fallbacks) == int(snap["handoff_fallbacks"])
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_train_chaos_journal_exact_once(tmp_path):
    """Training churn: a crash restart and an anomaly rollback each
    journal exactly once, checkpoint publications match the saves that
    actually happened, schema/bounds/ordering hold throughout."""
    import deepspeed_tpu
    import deepspeed_tpu.parallel.topology as topo
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.runtime.resilience import TrainingSupervisor

    rng = np.random.default_rng(0)

    def build(save_dir, faults):
        topo.reset_topology()
        cfg = {
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": -1, "fsdp": 1},
            "steps_per_print": 10**9,
            "resilience": {"enabled": True, "save_dir": str(save_dir),
                           "save_interval_steps": 2,
                           "restart_backoff_s": 0.01,
                           "restart_backoff_jitter": 0.0,
                           "watchdog_enabled": False,
                           "max_consecutive_anomalies": 2,
                           "faults": faults},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_model("tiny"), config=cfg,
            training_data={"input_ids": rng.integers(
                0, 256, size=(64, 33), dtype=np.int64)})
        return engine

    # crash at 3 AND a 2-step NaN storm at 5 -> one restart + one rollback
    faults = {"enabled": True, "schedule": [
        {"kind": "crash", "at_step": 3},
        {"kind": "nan_grads", "at_step": 5, "count": 2},
    ]}
    d = tmp_path / "churn"
    sup = TrainingSupervisor(engine=build(d, faults))
    r = sup.run(8)
    assert r["status"] == "completed"
    evs = _journal_invariants(sup.journal)
    kinds = [e["kind"] for e in evs]
    assert kinds.count("train_restart") == r["train_restarts"]
    assert kinds.count("train_anomaly_rollback") == \
        r["anomaly_rollbacks"] == 1
    # every train_restart detail matches the supervisor's restart log
    restarts = [e for e in evs if e["kind"] == "train_restart"]
    for ev, log in zip(restarts, sup.restart_log):
        assert ev["detail"]["reason"] == log["reason"]
        assert ev["detail"]["steps_lost"] == log["steps_lost"]
        assert ev["detail"]["resumed_step"] == log["resumed_step"]
    assert kinds.count("checkpoint_saved") >= 2
    assert kinds.count("train_parked") == 0


def test_autoscaling_storm_journal_exact_once():
    """ISSUE 12 satellite: an autoscaling storm — oscillating load
    driving hundreds of grow/shrink/re-role/brownout decisions through
    a deterministic fake-clock controller — journals every completed
    action EXACTLY once (1:1 against the controller's own decision log,
    kind for kind, field for field), schema-valid, bounded, monotonic."""
    from deepspeed_tpu.serving import AutoscalerConfig
    from deepspeed_tpu.serving.autoscaler import FleetController
    from deepspeed_tpu.telemetry import OpsJournal

    from test_autoscaler import FakeClock, FakeFleet

    clock = FakeClock()
    fleet = FakeFleet({0: FakeFleet.rep(role="prefill"),
                       1: FakeFleet.rep(role="decode")},
                      disaggregated=True, prefill_cost=1.0,
                      decode_cost=1.0)
    journal = OpsJournal(capacity=4096, clock=clock)
    ctl = FleetController(
        AutoscalerConfig(enabled=True, min_replicas=1, max_replicas=4,
                         scale_up_queue_per_replica=4.0,
                         scale_down_queue_per_replica=0.25,
                         scale_down_tokens_per_replica=4.0,
                         up_stable_ticks=1, down_stable_ticks=2,
                         scale_up_cooldown_s=1.0,
                         scale_down_cooldown_s=1.0,
                         rerole_stable_ticks=2, rerole_cooldown_s=3.0,
                         brownout_burn_threshold=2.0,
                         brownout_fraction=0.5),
        fleet, journal=journal, clock=clock, async_actions=False)
    rng = np.random.default_rng(0)
    for i in range(600):
        phase = (i // 40) % 4
        if phase == 0:          # burst: queue pressure + hot slow burn
            fleet.queue_depth = int(rng.integers(30, 60))
            fleet.burn_slow = 3.0
        elif phase == 1:        # drain: calm queue, burn recovering
            fleet.queue_depth = 0
            fleet.burn_slow = 0.5
        elif phase == 2:        # prefill-heavy mix
            fleet.queue_depth = 1
            for r in fleet.replicas.values():
                r.update(pre=50 if r["role"] == "prefill" else 0, dec=1)
        else:                   # decode-heavy mix
            fleet.queue_depth = 1
            for r in fleet.replicas.values():
                r.update(dec=50 if r["role"] != "prefill" else 0, pre=1)
        ctl.tick(clock.advance(1.0))
    evs = _journal_invariants(journal)
    assert len(journal) <= journal.capacity
    log = ctl.decision_log
    assert len(log) >= 20, "storm drove too few decisions to be a test"
    kinds = {"scale_up", "scale_down", "replica_reroled",
             "brownout_proactive"}
    assert {e["kind"] for e in evs} <= kinds
    assert {d["action"] for d in log} == {e["kind"] for e in evs}
    # exactly-once, order-preserving, field-for-field
    assert len(evs) == len(log)
    for ev, dec in zip(evs, log):
        assert ev["kind"] == dec["action"]
        for field in ev["detail"]:
            assert ev["detail"][field] == dec[field], (ev, dec)
    # the fleet never left its bounds, and never lost decode capability
    assert 1 <= len(fleet.replicas) <= 4
    assert any(r["role"] in ("decode", "mixed")
               for r in fleet.replicas.values())


def test_journal_stays_bounded_under_event_storm():
    """A pathological storm (far more events than capacity) keeps the
    ring at capacity with the NEWEST events, still schema-valid."""
    from deepspeed_tpu.telemetry import OpsJournal

    j = OpsJournal(capacity=32)
    for i in range(10_000):
        j.emit("train_wedge", step=i)
    assert len(j) == 32
    assert j.total_emitted == 10_000
    evs = j.events()
    assert validate_events(evs) == []
    assert evs[-1]["detail"]["step"] == 9_999
