"""Compiled-HLO collective analysis (utils/comms_logging.analyze_compiled):
the in-jit counterpart of the reference comms logger — per-op counts,
per-shard bytes, group sizes parsed from the optimized program."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils.comms_logging import (analyze_compiled,
                                               format_compiled_comms)


def test_analyze_compiled_psum(devices8):
    from deepspeed_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(devices8), ("data",))

    def f(x):
        return jax.lax.psum(x, "data")

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"),
                           out_specs=P(), check_vma=False))
    x = jnp.ones((8, 128), jnp.float32)
    report = analyze_compiled(fn.lower(x).compile())
    assert "all-reduce" in report
    rec = report["all-reduce"]
    assert rec["count"] >= 1
    assert rec["bytes"] == 128 * 4          # per-shard row of f32
    assert 8 in rec["group_sizes"]
    assert "f32" in rec["dtypes"]
    assert "all-reduce" in format_compiled_comms(report)


def test_engine_comms_report_zero3(devices8):
    """ZeRO-3 over fsdp shows param gathers/grad reduce traffic; the
    1-bit engine shows the int8 wire."""
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3},
                "mesh": {"data": -1, "fsdp": 4},
                "steps_per_print": 10**9})
    assert engine.mesh.shape["fsdp"] == 4
    report = engine.comms_report(print_log=False)
    assert any(op in report for op in ("all-gather", "all-reduce",
                                       "reduce-scatter")), report

    from deepspeed_tpu.parallel import topology as topo

    topo.reset_topology()
    onebit, _, _, _ = deepspeed_tpu.initialize(
        model=build_model("tiny"),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "OneBitAdam",
                              "params": {"lr": 1e-3, "freeze_step": 0}},
                "zero_optimization": {"stage": 0},
                "mesh": {"data": -1, "fsdp": 1},
                "steps_per_print": 10**9})
    rep1 = onebit.comms_report(print_log=False)
    # packed two-phase wire: sign bits ride u8 all-to-all + all-gather
    assert "u8" in rep1.get("all-to-all", {}).get("dtypes", set()), rep1
    assert "u8" in rep1.get("all-gather", {}).get("dtypes", set()), rep1
