"""Hybrid (RLHF) engine tests (reference
tests/unit/hybrid_engine/test_he_*): one engine alternates ZeRO-3 training
with generate rollouts on the same weights; the serving view tracks
training steps."""

import itertools

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import build_model
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedTpuHybridEngine


def make_engine(release_cache=False):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3},
        "mesh": {"data": -1, "fsdp": 2},
        "steps_per_print": 10**9,
        "hybrid_engine": {"enabled": True, "max_out_tokens": 8,
                          "release_inference_cache": release_cache},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=build_model("tiny"),
                                               config=cfg)
    return engine


def train_steps(engine, n, seed=0):
    rng = np.random.default_rng(seed)
    dp = engine.topology.get_data_parallel_world_size()
    batch = {"input_ids": rng.integers(0, 256, size=(2 * dp, 33),
                                       dtype=np.int64)}
    return [float(engine.train_batch(itertools.repeat(batch)))
            for _ in range(n)]


def test_initialize_selects_hybrid_engine(devices8):
    engine = make_engine()
    assert isinstance(engine, DeepSpeedTpuHybridEngine)


def test_rlhf_train_generate_loop(devices8):
    engine = make_engine()
    prompts = np.random.default_rng(0).integers(0, 256, size=(4, 8))

    losses = train_steps(engine, 2)
    engine.eval()
    out1 = np.asarray(engine.generate(prompts, max_new_tokens=4))
    assert out1.shape == (4, 12)
    engine.train()
    train_steps(engine, 2, seed=1)
    engine.eval()
    out2 = np.asarray(engine.generate(prompts, max_new_tokens=4))
    assert out2.shape == (4, 12)
    assert np.isfinite(losses).all()

    stats = engine.latency_stats()
    assert stats["generate_iters"] == 2
    assert stats["generate_latency_s"] > 0
    assert stats["training_latency_s"] > 0


def test_serving_view_tracks_training(devices8):
    engine = make_engine()
    train_steps(engine, 1)
    eng = engine._sync_inference_params()
    before = np.asarray(jax.tree.leaves(eng.params)[0]).copy()
    train_steps(engine, 3, seed=2)
    eng = engine._sync_inference_params()
    after = np.asarray(jax.tree.leaves(eng.params)[0])
    assert not np.allclose(before, after), \
        "serving params did not refresh after training steps"


def test_release_inference_cache(devices8):
    engine = make_engine(release_cache=True)
    train_steps(engine, 1)
    prompts = np.zeros((2, 4), dtype=np.int64)
    engine.generate(prompts, max_new_tokens=2)
    assert engine._infer_engine is None     # dropped after each rollout
