"""HF checkpoint import: numeric parity against transformers' torch forward.

Mirrors the reference's checkpoint-loading tests (the inference-v2 model
tests build HF checkpoints and pin the loaded model's logits)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import (CausalLM, config_from_hf, from_pretrained,
                                  is_hf_checkpoint, load_hf_checkpoint)
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.parallel.sharding import ZeroShardingPlan

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def llama_ckpt(tmp_path_factory):
    """Tiny HF-format Llama checkpoint (safetensors) + the torch model."""
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      rms_norm_eps=1e-5, tie_word_embeddings=False,
                      rope_theta=10000.0)
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg).eval()
    path = tmp_path_factory.mktemp("llama_ckpt")
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


@pytest.fixture(scope="module")
def gpt2_ckpt(tmp_path_factory):
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=96, n_embd=32, n_layer=2, n_head=4,
                     n_positions=64)
    torch.manual_seed(1)
    model = GPT2LMHeadModel(cfg).eval()
    path = tmp_path_factory.mktemp("gpt2_ckpt")
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def _hf_logits(model, tokens: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        return model(torch.tensor(tokens)).logits.float().numpy()


def test_config_from_hf_llama(llama_ckpt):
    path, _ = llama_ckpt
    with open(os.path.join(path, "config.json")) as f:
        cfg = config_from_hf(json.load(f))
    assert cfg.hidden_size == 32 and cfg.num_layers == 2
    assert cfg.kv_heads == 2 and cfg.norm == "rmsnorm"
    assert cfg.activation == "silu" and not cfg.tie_embeddings


def test_is_hf_checkpoint(llama_ckpt, tmp_path):
    path, _ = llama_ckpt
    assert is_hf_checkpoint(path)
    assert not is_hf_checkpoint(str(tmp_path))


def test_llama_forward_parity(llama_ckpt):
    path, hf_model = llama_ckpt
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, size=(2, 16))
    ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-4)


def test_gpt2_forward_parity(gpt2_ckpt):
    path, hf_model = gpt2_ckpt
    model, params = from_pretrained(path, dtype=jnp.float32,
                                    attention_impl="reference")
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 96, size=(2, 12))
    ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-4)


def test_torch_bin_roundtrip(llama_ckpt, tmp_path):
    """pytorch_model.bin (non-safetensors) shards load identically."""
    path, hf_model = llama_ckpt
    bin_dir = tmp_path / "bin_ckpt"
    hf_model.save_pretrained(bin_dir, safe_serialization=False)
    m1, p1 = from_pretrained(str(bin_dir), dtype=jnp.float32,
                             attention_impl="reference")
    _, p2 = from_pretrained(path, dtype=jnp.float32,
                            attention_impl="reference")
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), p1, p2)


def test_tp_sharded_load(llama_ckpt):
    """TP+fsdp sharded load reads per-device slices and matches the
    unsharded load (reference module_inject/load_checkpoint.py role)."""
    path, _ = llama_ckpt
    topo.reset_topology()
    t = topo.MeshTopology.build(tensor=2, fsdp=2, data=-1)
    try:
        model, _ = from_pretrained(path, dtype=jnp.float32)
        plan = ZeroShardingPlan(t, 3, model.param_specs())
        _, sharded = load_hf_checkpoint(path, model=model, sharding_plan=plan)
        _, full = load_hf_checkpoint(path, model=model)
        # every leaf equal once gathered; at least one leaf actually sharded
        some_sharded = [False]

        def check(a, b):
            if not a.sharding.is_fully_replicated:
                some_sharded[0] = True
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        jax.tree.map(check, sharded, full)
        assert some_sharded[0]
    finally:
        topo.reset_topology()


def test_v1_engine_checkpoint_path(llama_ckpt):
    """init_inference with only a checkpoint dir serves HF weights."""
    path, hf_model = llama_ckpt
    topo.reset_topology()
    engine = deepspeed_tpu.init_inference(model=None, checkpoint=path,
                                          dtype="fp32")
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 128, size=(1, 8))
    ours = np.asarray(engine.forward(tokens))
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-4)
    # greedy next token agrees
    out = np.asarray(engine.generate(tokens, max_new_tokens=1))
    assert out[0, -1] == theirs[0, -1].argmax()
    topo.reset_topology()


def test_v2_engine_checkpoint_path(llama_ckpt):
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2

    path, hf_model = llama_ckpt
    with open(os.path.join(path, "config.json")) as f:
        cfg = config_from_hf(json.load(f), dtype=jnp.float32)
    model = CausalLM(cfg)
    engine = InferenceEngineV2(model=model, checkpoint_path=path)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 128, size=(9,)).tolist()
    logits = np.asarray(engine.put([7], [prompt]))
    theirs = _hf_logits(hf_model, np.asarray([prompt]))[0, -1]
    np.testing.assert_allclose(logits[0], theirs, atol=3e-4, rtol=3e-4)


def test_encoder_explicit_model_type_without_config_json():
    """load_hf_checkpoint(model=..., model_type=...) with no config.json
    must not crash with TypeError(None + '.'): build_leaf_plans injects
    the explicit model_type, and a missing model_type raises a
    descriptive ValueError (ISSUE 1 satellite, ADVICE.md)."""
    from deepspeed_tpu.models.convert import (_encoder_prefix_and_heads,
                                              build_leaf_plans)
    from deepspeed_tpu.models.encoder import EncoderConfig, EncoderLM

    with pytest.raises(ValueError, match="model_type"):
        _encoder_prefix_and_heads({})

    model = EncoderLM(EncoderConfig(vocab_size=32, hidden_size=16,
                                    intermediate_size=32, num_layers=1,
                                    num_heads=2, max_seq_len=16))
    # explicit model_type + empty hf config: plans build, task-model
    # prefix assumed (no architectures info to say otherwise)
    plans = build_leaf_plans(model, "bert", {})
    assert "embed" in plans and "layers" in plans
