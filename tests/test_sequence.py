"""Sequence parallelism tests: Ulysses all-to-all attention and ring
attention vs dense reference (reference has only Ulysses —
deepspeed/sequence/layer.py; ring CP is a superset capability)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.transformer import (
    CausalLM, TINY_TEST, attention_reference)
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.sequence.ring_attention import ring_attention_sharded
from deepspeed_tpu.sequence.layer import DistributedAttention


def _qkv(B=2, T=32, H=4, D=16, KH=None, seed=0):
    rng = np.random.default_rng(seed)
    KH = KH or H
    q = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, T, KH, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, T, KH, D)).astype(np.float32))
    return q, k, v


def test_ring_attention_matches_dense():
    t = topo.MeshTopology.build(sequence=4, data=-1)
    topo.set_topology(t)
    q, k, v = _qkv(T=32)
    out = ring_attention_sharded(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gqa():
    t = topo.MeshTopology.build(sequence=2, data=-1)
    topo.set_topology(t)
    q, k, v = _qkv(T=16, H=8, KH=2)
    out = ring_attention_sharded(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads():
    t = topo.MeshTopology.build(sequence=2, data=-1)
    topo.set_topology(t)
    q, k, v = _qkv(T=16)

    g_ring = jax.grad(lambda q: jnp.sum(ring_attention_sharded(q, k, v)))(q)
    g_ref = jax.grad(lambda q: jnp.sum(attention_reference(q, k, v)))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=2e-4, atol=2e-5)


def test_distributed_attention_ulysses_matches_dense():
    t = topo.MeshTopology.build(sequence=4, data=-1)
    topo.set_topology(t)
    q, k, v = _qkv(T=32, H=4)

    da = DistributedAttention(lambda q, k, v: attention_reference(q, k, v, causal=True))
    out = jax.jit(da)(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["flash", "ring"])
def test_engine_trains_with_sequence_parallel(impl):
    cfg = dataclasses.replace(TINY_TEST, attention_impl=impl, num_kv_heads=4)
    model = CausalLM(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"data": -1, "sequence": 2},
        "steps_per_print": 100,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(engine.train_batch_size(), 33), dtype=np.int64)}
    losses = []
    for _ in range(6):
        loss = engine(data)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_seq_parallel_matches_single_device_loss():
    """The sequence-parallel loss must equal the unsharded computation."""
    cfg = dataclasses.replace(TINY_TEST, attention_impl="ring", num_kv_heads=4)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(4, 33), dtype=np.int64))}

    # dense single-mesh loss
    topo.reset_topology()
    t1 = topo.MeshTopology.build(data=-1)
    topo.set_topology(t1)
    loss_dense = float(model.loss(params, batch))

    topo.reset_topology()
    t2 = topo.MeshTopology.build(sequence=4, data=-1)
    topo.set_topology(t2)
    loss_sp = float(model.loss(params, batch))
    np.testing.assert_allclose(loss_sp, loss_dense, rtol=1e-4)


@pytest.mark.parametrize("window", [5, 12])
def test_ring_attention_sliding_window(window):
    """Windowed ring attention (long-context Mistral under context
    parallelism) matches the dense windowed reference, including blocks
    wholly outside the band."""
    t = topo.MeshTopology.build(sequence=4, data=-1)
    topo.set_topology(t)
    q, k, v = _qkv(T=32)
    out = ring_attention_sharded(q, k, v, causal=True, window=window)
    ref = attention_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_sliding_window_grads():
    t = topo.MeshTopology.build(sequence=2, data=-1)
    topo.set_topology(t)
    q, k, v = _qkv(T=16)
    g_ring = jax.grad(lambda q: jnp.sum(
        ring_attention_sharded(q, k, v, window=6)))(q)
    g_ref = jax.grad(lambda q: jnp.sum(
        attention_reference(q, k, v, window=6)))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["flash", "ring"])
def test_windowed_model_under_sequence_parallelism(impl):
    """A sliding-window model trained under a sequence mesh axis (Ulysses
    or ring) reproduces the single-device loss."""
    cfg = dataclasses.replace(TINY_TEST, num_kv_heads=4,
                              sliding_window=8, attention_impl=impl,
                              use_flash_attention=False)
    model_cfg = {"train_micro_batch_size_per_gpu": 2,
                 "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                 "zero_optimization": {"stage": 0},
                 "steps_per_print": 10**9}
    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(0, 256, size=(8, 33),
                                      dtype=np.int64)}

    topo.reset_topology()
    single, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(dataclasses.replace(cfg, attention_impl="reference")),
        config=dict(model_cfg, mesh={"data": -1, "fsdp": 1}))
    loss_single = float(single(dict(data)))

    topo.reset_topology()
    sp, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(cfg),
        config=dict(model_cfg, mesh={"data": 2, "sequence": 4}))
    loss_sp = float(sp(dict(data)))
    np.testing.assert_allclose(loss_sp, loss_single, rtol=2e-5)
    topo.reset_topology()
