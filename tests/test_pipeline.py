"""Pipeline parallelism tests (reference tests/unit/runtime/pipe/
test_topology.py and test_pipe_schedule.py, plus SPMD pipeline execution)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.compat import PARTIAL_AUTO_SHARD_MAP
from deepspeed_tpu.models.transformer import CausalLM, TINY_TEST
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.parallel.pipeline import pipelined_layer_apply
from deepspeed_tpu.runtime.pipe import (
    ProcessTopology, PipeModelDataParallelTopology, TrainSchedule,
    InferenceSchedule, ForwardPass, BackwardPass, LoadMicroBatch,
    OptimizerStep, LayerSpec, PipelineModule)
from deepspeed_tpu.runtime.pipe.module import partition_balanced


# ---------------------------------------------------------------- topology
_partial_auto = pytest.mark.skipif(
    not PARTIAL_AUTO_SHARD_MAP,
    reason="installed jax lacks usable partial-auto shard_map "
           "(no eager impl / PartitionId under CPU SPMD)")


def test_process_topology_rank_mapping():
    t = ProcessTopology(axes=["pipe", "data"], dims=[2, 4])
    assert t.world_size() == 8
    assert t.get_rank(pipe=0, data=0) == 0
    assert t.get_rank(pipe=0, data=3) == 3
    assert t.get_rank(pipe=1, data=0) == 4
    assert t.get_coord(5) == t.ProcessCoord(pipe=1, data=1)


def test_axis_comm_lists():
    t = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    pipe_groups = t.get_axis_comm_lists("pipe")
    assert len(pipe_groups) == 4
    for g in pipe_groups:
        assert len(g) == 2
    assert t.filter_match(pipe=0) == [0, 1, 2, 3]


def test_rank_repr():
    t = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=1)
    r = t.get_rank_repr(t.get_rank(pipe=1, data=0, model=1))
    assert "pipe_01" in r and "model_01" in r


# ---------------------------------------------------------------- schedules
def test_inference_schedule_covers_all_microbatches():
    sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = sched.steps()
    fwd = [c for cmds in steps for c in cmds if isinstance(c, ForwardPass)]
    loads = [c for cmds in steps for c in cmds if isinstance(c, LoadMicroBatch)]
    assert len(fwd) == 4
    assert len(loads) == 4  # first stage loads every microbatch


@pytest.mark.parametrize("stages,stage_id", [(2, 0), (2, 1), (4, 2)])
def test_train_schedule_1f1b_counts(stages, stage_id):
    M = 6
    sched = TrainSchedule(micro_batches=M, stages=stages, stage_id=stage_id)
    steps = sched.steps()
    fwd = [c for cmds in steps for c in cmds if isinstance(c, ForwardPass)]
    bwd = [c for cmds in steps for c in cmds if isinstance(c, BackwardPass)]
    opt = [c for cmds in steps for c in cmds if isinstance(c, OptimizerStep)]
    assert len(fwd) == M
    assert len(bwd) == M
    assert len(opt) == 1
    # every microbatch forwarded before its backward
    fwd_order = [c.buffer_id for cmds in steps for c in cmds
                 if isinstance(c, ForwardPass)]
    assert len(fwd_order) == M


# ------------------------------------------------------------- partitioning
def test_partition_balanced_uniform():
    parts = partition_balanced([1.0] * 8, 4)
    assert parts[0] == 0 and parts[-1] == 8
    sizes = [parts[i + 1] - parts[i] for i in range(4)]
    assert sizes == [2, 2, 2, 2]


def test_partition_balanced_weighted():
    # one huge layer should sit alone
    parts = partition_balanced([10.0, 1.0, 1.0, 1.0], 2)
    assert parts == [0, 1, 4]


def test_pipeline_module_stage_assignment():
    class Dummy:
        def __init__(self, n):
            self.n = n

        def num_params(self):
            return self.n

    layers = [LayerSpec(Dummy, 100), LayerSpec(Dummy, 1), LayerSpec(Dummy, 1),
              LayerSpec(Dummy, 100)]
    pm = PipelineModule(layers, num_stages=2, partition_method="parameters")
    assert pm.stage_owner(0) == 0
    assert pm.stage_owner(3) == 1
    assert len(pm.stage_layers(0)) + len(pm.stage_layers(1)) == 4


# ---------------------------------------------------------- SPMD execution
@_partial_auto
def test_spmd_pipeline_matches_sequential():
    """Pipelined layer apply must equal the plain scan."""
    t = topo.MeshTopology.build(pipe=4, data=-1)
    topo.set_topology(t)
    L, B, T, H = 8, 4, 8, 16
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(L, H, H)).astype(np.float32)) * 0.1
    x = jnp.asarray(rng.normal(size=(B, T, H)).astype(np.float32))

    def layer_fn(carry, wi, micro_idx):
        return jnp.tanh(carry @ wi), jnp.zeros((), jnp.float32)

    out_pipe, _aux = pipelined_layer_apply(layer_fn, w, x, num_micro=4, mesh=t.mesh)

    def seq(x):
        for i in range(L):
            x = jnp.tanh(x @ w[i])
        return x

    np.testing.assert_allclose(np.asarray(out_pipe), np.asarray(seq(x)),
                               rtol=2e-5, atol=2e-5)


@_partial_auto
def test_spmd_pipeline_grads_match():
    t = topo.MeshTopology.build(pipe=2, data=-1)
    topo.set_topology(t)
    L, B, T, H = 4, 4, 4, 8
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(L, H, H)).astype(np.float32)) * 0.1
    x = jnp.asarray(rng.normal(size=(B, T, H)).astype(np.float32))

    def layer_fn(carry, wi, micro_idx):
        return jnp.tanh(carry @ wi), jnp.zeros((), jnp.float32)

    def loss_pipe(w):
        out, _aux = pipelined_layer_apply(layer_fn, w, x, 2, mesh=t.mesh)
        return jnp.sum(out ** 2)

    def loss_seq(w):
        y = x
        for i in range(L):
            y = jnp.tanh(y @ w[i])
        return jnp.sum(y ** 2)

    g1 = jax.grad(loss_pipe)(w)
    g2 = jax.grad(loss_seq)(w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-4, atol=2e-5)


@_partial_auto
def test_engine_trains_with_pipeline_parallel():
    cfg = dataclasses.replace(TINY_TEST, num_kv_heads=4)
    model = CausalLM(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": -1, "pipe": 2},
        "pipeline": {"stages": 2, "micro_batches": 4},
        "steps_per_print": 100,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    # layer stack sharded over pipe axis
    wq = engine.state.params["layers"]["wq"]
    assert "pipe" in str(wq.sharding.spec)

    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(engine.train_batch_size(), 33), dtype=np.int64)}
    losses = []
    for _ in range(6):
        loss = engine(data)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


@_partial_auto
def test_pipeline_matches_unpipelined_loss():
    cfg = dataclasses.replace(TINY_TEST, num_kv_heads=4, pipeline_microbatches=2)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(4, 33), dtype=np.int64))}

    topo.reset_topology()
    topo.set_topology(topo.MeshTopology.build(data=-1))
    loss_dense = float(model.loss(params, batch))

    topo.reset_topology()
    topo.set_topology(topo.MeshTopology.build(pipe=2, data=-1))
    loss_pp = float(model.loss(params, batch))
    np.testing.assert_allclose(loss_pp, loss_dense, rtol=1e-4)


@_partial_auto
def test_pipeline_moe_aux_loss_nonzero():
    """MoE aux loss must flow out of the pipelined path (not silently zero)."""
    cfg = dataclasses.replace(TINY_TEST, num_kv_heads=4, moe_num_experts=4,
                              moe_capacity_factor=2.0, pipeline_microbatches=2)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(4, 32), dtype=np.int64))

    topo.reset_topology()
    topo.set_topology(topo.MeshTopology.build(pipe=2, data=-1))
    _, aux = model.apply(params, batch, return_aux=True)
    assert float(aux) > 0, "pipelined MoE aux loss is zero"

    topo.reset_topology()
    topo.set_topology(topo.MeshTopology.build(data=-1))
    _, aux_dense = model.apply(params, batch, return_aux=True)
    # microbatched gating differs slightly from full-batch gating, but the
    # magnitudes must agree
    np.testing.assert_allclose(float(aux), float(aux_dense), rtol=0.3)
