"""Launcher tests (reference tests/unit/launcher/test_run.py: hostfile
parsing and resource filters)."""

import pytest

from deepspeed_tpu.launcher.runner import fetch_hostfile, parse_resource_filter


def test_fetch_hostfile(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("""
# comment
worker-0 slots=4
worker-1 slots=4   # trailing comment
worker-2
""")
    hosts = fetch_hostfile(str(hf))
    assert hosts == {"worker-0": 4, "worker-1": 4, "worker-2": 1}


def test_fetch_hostfile_missing():
    assert fetch_hostfile("/nonexistent/hostfile") == {}


def test_duplicate_host_raises(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("a slots=1\na slots=2\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(hf))


def test_include_filter():
    hosts = {"a": 4, "b": 4, "c": 4}
    assert parse_resource_filter(hosts, include="a@c") == {"a": 4, "c": 4}


def test_exclude_filter():
    hosts = {"a": 4, "b": 4}
    assert parse_resource_filter(hosts, exclude="b") == {"a": 4}


def test_include_and_exclude_conflict():
    with pytest.raises(ValueError):
        parse_resource_filter({"a": 1}, include="a", exclude="a")


def test_unknown_host_raises():
    with pytest.raises(ValueError):
        parse_resource_filter({"a": 1}, include="zzz")


def test_slot_filter_rejected():
    with pytest.raises(ValueError):
        parse_resource_filter({"a": 4}, include="a:0,1")


def test_env_report_runs(capsys):
    from deepspeed_tpu.env_report import main

    main()
    out = capsys.readouterr().out
    assert "deepspeed_tpu version" in out
    assert "accelerator" in out


def test_chip_assignment_defaults():
    """--launcher local per-rank TPU_VISIBLE_CHIPS defaults: an even slice
    of the host's chips per rank; no default when chips are unknown or
    oversubscribed (the script/env then owns partitioning)."""
    from deepspeed_tpu.launcher.runner import chip_assignment

    assert chip_assignment(4, 2, 0) == "0,1"
    assert chip_assignment(4, 2, 1) == "2,3"
    assert chip_assignment(4, 4, 3) == "3"
    assert chip_assignment(8, 2, 1) == "4,5,6,7"
    # 3 ranks on 4 chips: floor slice of 1 chip each, chip 3 idle
    assert chip_assignment(4, 3, 2) == "2"
    assert chip_assignment(0, 2, 0) is None     # no chips detected
    assert chip_assignment(2, 4, 0) is None     # more ranks than chips


def test_tpu_process_env_per_rank():
    """TPU_VISIBLE_CHIPS alone still collides on real single-host
    multi-process TPU: each rank also needs a distinct libtpu
    coordination endpoint and task id (ISSUE 1 satellite)."""
    from deepspeed_tpu.launcher.runner import (TPU_PROCESS_BASE_PORT,
                                               tpu_process_env)

    e0 = tpu_process_env(2, 0)
    e1 = tpu_process_env(2, 1)
    # distinct per-rank ports, shared full endpoint list, rank as task id
    assert e0["TPU_PROCESS_PORT"] != e1["TPU_PROCESS_PORT"]
    assert e0["TPU_PROCESS_ADDRESSES"] == e1["TPU_PROCESS_ADDRESSES"]
    assert e0["TPU_PROCESS_ADDRESSES"] == (
        f"127.0.0.1:{TPU_PROCESS_BASE_PORT},"
        f"127.0.0.1:{TPU_PROCESS_BASE_PORT + 1}")
    assert e0["CLOUD_TPU_TASK_ID"] == "0"
    assert e1["CLOUD_TPU_TASK_ID"] == "1"
    # custom base port counts up from there
    assert tpu_process_env(4, 3, base_port=9000)["TPU_PROCESS_PORT"] == "9003"
