"""Unit surface of deepspeed_tpu/serving/: metrics, admission queue,
router selection/health. No engine involved — these are the pieces the
load test (test_serving_load.py) composes end-to-end."""

import threading
import time

import pytest

from deepspeed_tpu.serving import (AdmissionQueue, FinishReason,
                                   MetricsRegistry, Priority, Rejected,
                                   RequestState, ServingConfig,
                                   ServingRequest, serving_metrics)
from deepspeed_tpu.serving.metrics import Counter, Gauge, Histogram


def _req(priority=Priority.NORMAL, deadline_s=None, prompt_len=4,
         max_new=4):
    return ServingRequest([1] * prompt_len, max_new, priority, deadline_s,
                          None)


# ------------------------------------------------------------------ metrics
def test_counter_gauge():
    c = Counter()
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    g = Gauge()
    g.set(7)
    g.dec(3)
    assert g.value == 4.0


def test_histogram_percentiles():
    h = Histogram(buckets=(1.0, 2.0, 4.0, 8.0))
    for v in (0.5, 1.5, 1.5, 3.0, 7.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(13.5)
    # p50 lands in the (1, 2] bucket, p99 in (4, 8]
    assert 1.0 <= h.percentile(50) <= 2.0
    assert 4.0 <= h.percentile(99) <= 8.0
    assert h.percentile(0) <= 1.0


def test_histogram_overflow_bucket():
    h = Histogram(buckets=(1.0, 2.0))
    h.observe(100.0)
    # overflow estimate floors at the last finite bound
    assert h.percentile(50) == 2.0
    assert h.mean == pytest.approx(100.0)


def test_registry_snapshot_and_events():
    reg = MetricsRegistry("serving")
    reg.counter("requests_completed").inc(3)
    reg.gauge("queue_depth").set(5)
    reg.histogram("ttft_s").observe(0.02)
    snap = reg.snapshot()
    assert snap["requests_completed"] == 3
    assert snap["queue_depth"] == 5
    assert snap["ttft_s"]["count"] == 1
    tags = {t for t, _, _ in reg.events(step=7)}
    assert "serving/requests_completed" in tags
    assert "serving/ttft_s/p95" in tags
    assert all(s == 7 for _, _, s in reg.events(step=7))


def test_registry_monitor_fanout(tmp_path):
    """Serving metrics flow through the existing monitor/ CSV backend."""
    from deepspeed_tpu.monitor import CSVMonitor

    reg = serving_metrics()
    reg.counter("requests_completed").inc(2)
    mon = CSVMonitor(str(tmp_path), job_name="serve")
    reg.publish(mon, step=1)
    out = tmp_path / "serve" / "serving_requests_completed.csv"
    assert out.exists()
    assert "2.0" in out.read_text()


def test_predeclared_serving_metrics():
    reg = serving_metrics()
    snap = reg.snapshot()
    assert snap["requests_shed"] == 0.0
    assert snap["ttft_s"]["count"] == 0.0


# ------------------------------------------------------------------- queue
def test_queue_sheds_when_full():
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=2, metrics=reg)
    q.offer(_req())
    q.offer(_req())
    with pytest.raises(Rejected) as ei:
        q.offer(_req())
    assert ei.value.reason == "overloaded"
    assert reg.snapshot()["requests_shed"] == 1
    assert len(q) == 2           # bounded: the shed request never entered


def test_queue_shed_request_gets_terminal_state():
    q = AdmissionQueue(max_depth=1)
    q.offer(_req())
    shed = _req()
    with pytest.raises(Rejected):
        q.offer(shed)
    assert shed.state == RequestState.REJECTED
    assert shed.wait(0)          # stream terminated, not hanging


def test_queue_priority_then_deadline_order():
    q = AdmissionQueue(max_depth=10)
    low = _req(priority=Priority.LOW)
    high = _req(priority=Priority.HIGH)
    tight = _req(priority=Priority.NORMAL, deadline_s=10.0)
    loose = _req(priority=Priority.NORMAL, deadline_s=60.0)
    none = _req(priority=Priority.NORMAL, deadline_s=None)
    for r in (none, low, loose, tight, high):
        q.offer(r)
    order = [q.pop(timeout=0.1).uid for _ in range(5)]
    assert order == [high.uid, tight.uid, loose.uid, none.uid, low.uid]


def test_queue_expires_stale_requests_at_pop():
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=10, metrics=reg)
    stale = _req(deadline_s=0.01)
    fresh = _req(deadline_s=60.0)
    q.offer(stale)
    q.offer(fresh)
    time.sleep(0.05)
    got = q.pop(timeout=0.5)
    assert got is fresh
    assert stale.state == RequestState.EXPIRED
    assert stale.finish_reason == FinishReason.DEADLINE
    assert reg.snapshot()["requests_expired"] == 1


def test_queue_pop_skips_cancelled():
    q = AdmissionQueue(max_depth=10)
    a, b = _req(), _req()
    q.offer(a)
    q.offer(b)
    a.cancel_requested.set()
    assert q.pop(timeout=0.5) is b
    assert a.state == RequestState.CANCELLED


def test_queue_pop_blocks_until_offer():
    q = AdmissionQueue(max_depth=4)
    got = []

    def popper():
        got.append(q.pop(timeout=5.0))

    t = threading.Thread(target=popper)
    t.start()
    time.sleep(0.05)
    r = _req()
    q.offer(r)
    t.join(5.0)
    assert got and got[0] is r


def test_queue_close_drains():
    q = AdmissionQueue(max_depth=4)
    r = _req()
    q.offer(r)
    left = q.close()
    assert left == [r]
    with pytest.raises(Rejected) as ei:
        q.offer(_req())
    assert ei.value.reason == "draining"
    assert q.pop(timeout=0.1) is None


def test_queue_wait_histogram_populated():
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=4, metrics=reg)
    q.offer(_req())
    q.pop(timeout=0.5)
    assert reg.snapshot()["queue_wait_s"]["count"] == 1


# ------------------------------------------------------------------- router
class _FakeEngine:
    """Engine stand-in: enough surface for Replica/scheduler to exist."""

    class _Cfg:
        max_ragged_batch_size = 64
        max_ragged_sequence_count = 4
        max_chunk_tokens = 16

    class _MCfg:
        max_seq_len = 128

    class _Model:
        cfg = None

    def __init__(self):
        self.config = self._Cfg()
        self.model = self._Model()
        self.model.cfg = self._MCfg()
        self.flushed = []

    def flush(self, uid):
        self.flushed.append(uid)


def _router(n=2, start=False):
    from deepspeed_tpu.serving import ReplicaRouter
    from deepspeed_tpu.serving.replica import Replica

    reg = serving_metrics()
    q = AdmissionQueue(max_depth=16, metrics=reg)
    reps = [Replica(i, _FakeEngine(), reg) for i in range(n)]
    router = ReplicaRouter(reps, q, reg)
    if start:
        router.start()
    return router, reps, q, reg


def test_router_picks_least_outstanding_tokens():
    router, (r0, r1), _, _ = _router()
    r0.assign(_req(prompt_len=100, max_new=50))
    assert router.pick() is r1
    r1.assign(_req(prompt_len=100, max_new=100))
    assert router.pick() is r0


def test_router_skips_draining_and_dead():
    from deepspeed_tpu.serving import ReplicaState

    router, (r0, r1), _, reg = _router()
    r0.drain()
    assert router.pick() is r1
    r1.state = ReplicaState.DEAD
    assert router.pick() is None
    assert reg.snapshot()["replicas_healthy"] == 0


def test_router_fails_fast_with_no_replicas():
    from deepspeed_tpu.serving import ReplicaState

    router, reps, q, reg = _router()
    for r in reps:
        r.state = ReplicaState.DEAD
    req = _req()
    router._dispatch(req)
    assert req.state == RequestState.FAILED
    assert req.wait(0)
    assert reg.snapshot()["requests_failed"] == 1


def test_replica_wedge_detection():
    from deepspeed_tpu.serving import ReplicaState
    from deepspeed_tpu.serving.replica import Replica

    r = Replica(0, _FakeEngine(), wedge_timeout_s=0.01)
    # simulate: past warm-up, has work, no progress for > wedge_timeout
    r._steps_done = 1
    r._busy_since = time.monotonic() - 1.0
    r.last_progress_t = time.monotonic() - 1.0
    assert r.check_health() == ReplicaState.DEAD
    # idle replicas are never wedged
    r2 = Replica(1, _FakeEngine(), wedge_timeout_s=0.01)
    r2.last_progress_t = time.monotonic() - 1.0
    assert r2.check_health() == ReplicaState.HEALTHY
    # a cold replica stuck in its FIRST step is compiling, not wedged
    r3 = Replica(2, _FakeEngine(), wedge_timeout_s=0.01)
    r3._busy_since = time.monotonic() - 1.0
    r3.last_progress_t = time.monotonic() - 1.0
    assert r3.check_health() == ReplicaState.HEALTHY


def test_serving_config_in_runtime_config():
    from deepspeed_tpu.runtime.config import load_config

    cfg = load_config({"serving": {"max_queue_depth": 7, "num_replicas": 3,
                                   "default_deadline_ms": 250.0}})
    assert cfg.serving.max_queue_depth == 7
    assert cfg.serving.num_replicas == 3
    assert cfg.serving.default_deadline_ms == 250.0
    # defaults survive an absent block
    assert load_config({}).serving.shed_policy == "reject"


def test_replica_engine_fault_fails_requests_terminally():
    """A replica whose engine raises mid-step goes DEAD and every
    in-flight request reaches a terminal FAILED state (streams must not
    hang on a dead replica)."""
    from deepspeed_tpu.serving import ReplicaState
    from deepspeed_tpu.serving.replica import Replica

    class ExplodingEngine(_FakeEngine):
        def can_schedule(self, uids, lengths):
            raise RuntimeError("device wedged")

        def put(self, uids, tokens):
            raise RuntimeError("device wedged")

    reg = serving_metrics()
    r = Replica(0, ExplodingEngine(), reg)
    req = _req()
    assert r.assign(req)
    r.start()
    assert req.wait(10), "request never reached a terminal state"
    assert req.state == RequestState.FAILED
    assert r.state == ReplicaState.DEAD
    assert reg.snapshot()["requests_failed"] == 1
    r.stop(1.0)


def test_queue_blocking_offer_waits_for_room():
    """shed_policy="block": a full queue makes offer(block=True) wait for
    room, and the request is admitted ONCE, never shed-finished."""
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=1, metrics=reg)
    first = _req()
    q.offer(first)
    blocked = _req()
    done = threading.Event()

    def offerer():
        q.offer(blocked, block=True, timeout=5.0)
        done.set()

    t = threading.Thread(target=offerer)
    t.start()
    time.sleep(0.05)
    assert not done.is_set(), "offer should still be blocked on a full queue"
    assert q.pop(timeout=1.0) is first      # frees the slot
    assert done.wait(5.0), "blocked offer never admitted"
    t.join(5.0)
    assert q.pop(timeout=1.0) is blocked
    assert blocked.state != RequestState.REJECTED
    assert reg.snapshot()["requests_shed"] == 0


def test_queue_blocking_offer_timeout_sheds_once():
    q = AdmissionQueue(max_depth=1)
    q.offer(_req())
    late = _req()
    with pytest.raises(Rejected) as ei:
        q.offer(late, block=True, timeout=0.05)
    assert ei.value.reason == "overloaded"
    assert late.state == RequestState.REJECTED


def test_wedged_replica_fails_inflight_requests():
    """check_health marking a replica DEAD (worker stuck in a device
    call) must terminate its in-flight requests — no stream may hang."""
    from deepspeed_tpu.serving import ReplicaState
    from deepspeed_tpu.serving.replica import Replica

    reg = serving_metrics()
    r = Replica(0, _FakeEngine(), reg, wedge_timeout_s=0.01)
    req = _req()
    # simulate a worker wedged mid-step with this request active
    r._steps_done = 1
    r._active[req.uid] = req
    r._busy_since = time.monotonic() - 1.0
    r.last_progress_t = time.monotonic() - 1.0
    assert r.check_health() == ReplicaState.DEAD
    assert req.wait(1.0), "wedged replica left the request hanging"
    assert req.state == RequestState.FAILED
    assert reg.snapshot()["requests_failed"] == 1


def test_custom_ttft_buckets_take_effect():
    """ServingConfig.ttft_buckets_s must actually re-bucket the
    pre-declared ttft histogram (registry reset path)."""
    reg = serving_metrics()
    h = reg.histogram("ttft_s", (0.5, 1.0), reset=True)
    assert h.bounds == (0.5, 1.0)
    assert reg.histogram("ttft_s") is h


def test_queue_remove_frees_slot():
    q = AdmissionQueue(max_depth=2)
    a, b = _req(), _req()
    q.offer(a)
    q.offer(b)
    assert q.remove(a) is True
    assert q.remove(a) is False          # already out
    assert len(q) == 1
    q.offer(_req())                      # freed slot is usable again
    assert q.pop(timeout=0.5) is b


def test_pop_sweeps_expired_entries_anywhere_in_heap():
    """Deadline sweep at pop time covers the WHOLE heap: an expired LOW
    request buried under fresh HIGH traffic is failed with DEADLINE on
    the next pop instead of occupying a depth slot until it surfaces."""
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=10, metrics=reg)
    buried = _req(priority=Priority.LOW, deadline_s=0.01)
    tops = [_req(priority=Priority.HIGH, deadline_s=60.0) for _ in range(3)]
    q.offer(buried)
    for r in tops:
        q.offer(r)
    time.sleep(0.05)
    got = q.pop(timeout=0.5)
    assert got is tops[0]                # urgency order unchanged
    # the buried request was swept by that same pop, not left queued
    assert buried.state == RequestState.EXPIRED
    assert buried.finish_reason == FinishReason.DEADLINE
    assert buried.wait(0)                # stream terminated
    assert len(q) == 2
    assert reg.snapshot()["requests_expired"] == 1


# ------------------------------------------------------ replica lifecycle
class _CompletingFakeEngine(_FakeEngine):
    """Fake engine whose scheduler path actually completes requests:
    constant logits, every chunk schedulable — enough surface to run the
    worker loop end-to-end without JAX."""

    def can_schedule(self, uids, lengths):
        from deepspeed_tpu.inference.v2.scheduling_utils import (
            SchedulingResult)

        return SchedulingResult.Success

    def put(self, uids, chunks, **kw):
        import numpy as np

        return np.zeros((len(uids), 8), dtype=np.float32)

    def match_prefix(self, uid, prompt_tokens):
        return 0


def test_check_health_on_draining_replica():
    """DRAINING is not DEAD: check_health reports it untouched while the
    replica makes progress, but a WEDGED draining replica still crosses
    to DEAD (drain must not disable the watchdog)."""
    from deepspeed_tpu.serving import ReplicaState
    from deepspeed_tpu.serving.replica import Replica

    r = Replica(0, _FakeEngine(), wedge_timeout_s=0.01)
    r.drain()
    assert r.check_health() == ReplicaState.DRAINING
    # now simulate a wedge while draining: watchdog still fires
    r._steps_done = 1
    r._busy_since = time.monotonic() - 1.0
    r.last_progress_t = time.monotonic() - 1.0
    assert r.check_health() == ReplicaState.DEAD


def test_assign_racing_drain():
    """assign() after drain() refuses; an assign that WON the race (the
    request entered the inbox before DRAINING) still runs to completion
    — drain finishes accepted work, it never drops it."""
    from deepspeed_tpu.serving import ReplicaState
    from deepspeed_tpu.serving.replica import Replica

    reg = serving_metrics()
    r = Replica(0, _CompletingFakeEngine(), reg)
    won = _req(prompt_len=3, max_new=2)
    assert r.assign(won) is True         # accepted while HEALTHY
    r.drain()
    lost = _req()
    assert r.assign(lost) is False       # refused while DRAINING
    assert lost.state == RequestState.QUEUED   # untouched, router retries
    r.start()
    assert won.wait(10), "drain dropped an accepted request"
    assert won.state == RequestState.FINISHED
    r.thread.join(10)
    assert r.state == ReplicaState.STOPPED     # drained to completion
    r.stop(1.0)


def test_double_stop_idempotent():
    from deepspeed_tpu.serving import ReplicaState
    from deepspeed_tpu.serving.replica import Replica

    r = Replica(0, _CompletingFakeEngine(), serving_metrics())
    r.start()
    r.stop(2.0)
    assert r.state == ReplicaState.STOPPED
    r.stop(2.0)                          # second stop: no-op, no raise
    assert r.state == ReplicaState.STOPPED
    assert not r.thread.is_alive()


def test_fault_tolerance_config_in_runtime_config():
    from deepspeed_tpu.runtime.config import load_config

    cfg = load_config({"serving": {
        "fault_tolerance": {"enabled": True, "max_retries": 5,
                            "brownout_threshold": 0.5},
        "faults": {"enabled": True, "seed": 7, "schedule": [
            {"kind": "crash", "replica": 0, "at_step": 3}]}}})
    ft = cfg.serving.fault_tolerance
    assert ft.enabled and ft.max_retries == 5
    assert ft.brownout_threshold == 0.5
    inj = cfg.serving.faults.build_injector()
    assert inj is not None and inj.events[0].at_step == 3
    # defaults: both off, injector not built
    dflt = load_config({}).serving
    assert not dflt.fault_tolerance.enabled
    assert dflt.faults.build_injector() is None
