"""Frontend federation (ISSUE 18, docs/SERVING.md "Frontend
federation").

Two-tier serving fleet: a frontend with ``fabric.federation.enabled``
exports a slice of its LOCAL replica pool on ``fabric.listen`` while
adopting peer frontends' exports as routable federated members. Covers
the topology edges (self-peering refusal, wrong hello role, stale-epoch
rejection with newer-epoch supersession, no transitive re-export), the
shared pool (greedy byte-parity through an adopter with and without
local engines, per-peer capacity accounting via the status stream's
``active_total`` and ``peer_max_inflight``), cross-frontend failover
(killing a REAL subprocess frontend mid-burst — the adopter's in-flight
federated work resumes byte-losslessly on its local replica), local
evacuation draining onto a peer, removal of a federated member
requeueing its mirrors, and ``federation`` disabled being byte-for-byte
the single-frontend fabric stack.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.serving import ServingConfig, ServingFrontend
from deepspeed_tpu.serving.fabric.federation import (FederatedHandle,
                                                     FederationPeer,
                                                     FederationRefused,
                                                     derive_frontend_id)
from deepspeed_tpu.serving.fabric.transport import FabricError, dial

VOCAB = 128
MODEL_KW = dict(vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=2, max_seq_len=256, norm="rmsnorm",
                activation="silu", position="rope")
ENGINE_KW = dict(max_ragged_batch_size=128, max_ragged_sequence_count=4,
                 max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
                 max_tracked_sequences=32)
SEED = 0

_model = None
_params = None


def tiny_engine(i=0, **cfg_over):
    """Fresh engine over a module-shared model + seeded params — the
    SAME weights every frontend (in-process or subprocess) builds from
    the spec, so cross-frontend parity is byte-meaningful."""
    global _model, _params
    import jax

    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    if _model is None:
        _model = CausalLM(TransformerConfig(**MODEL_KW))
        _params = _model.init(jax.random.PRNGKey(SEED))
    base = dict(ENGINE_KW)
    base.update(cfg_over)
    return InferenceEngineV2(_model, params=_params,
                             config=RaggedInferenceEngineConfig(**base))


def prompts(n, seed, lo=8, hi=24):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(length)).tolist()
            for length in rng.integers(lo, hi, size=n)]


def run_fleet(fe, ps, max_new, timeout=300):
    hs = [fe.submit(p, max_new_tokens=max_new) for p in ps]
    assert fe.wait_all(hs, timeout=timeout), [h.state for h in hs]
    return [[ev.token for ev in h.drain()] for h in hs]


def local_reference(ps, max_new, n_replicas=1):
    fe = ServingFrontend([tiny_engine(i) for i in range(n_replicas)],
                         ServingConfig(max_queue_depth=64))
    try:
        return run_fleet(fe, ps, max_new)
    finally:
        fe.shutdown(drain=False, timeout=5)


def fed_cfg(peers=(), heartbeat_s=0.3, federation_extra=None, **extra):
    fed = {"enabled": True, "peers": list(peers)}
    fed.update(federation_extra or {})
    return ServingConfig(
        max_queue_depth=64,
        fabric={"enabled": True, "listen": "127.0.0.1:0",
                "heartbeat_s": heartbeat_s, "rpc_timeout_s": 60.0,
                "federation": fed},
        **extra)


def federated_rid(fe):
    return next(r.replica_id for r in fe.router.replicas
                if getattr(r, "is_federated", False))


# ======================================================== peering edges
class TestPeeringEdges:
    def test_self_peering_refused_typed(self):
        fe = ServingFrontend([tiny_engine(0)], fed_cfg())
        try:
            peer = FederationPeer(fe.federation_address, fe.config.fabric,
                                  frontend_id=fe._federation_id,
                                  epoch=fe._federation_epoch + 5)
            with pytest.raises(FederationRefused, match="self_peering"):
                peer.connect()
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_replica_role_hello_refused(self):
        """The federation listener speaks hello role 'frontend' only —
        a replica-shaped hello (e.g. a misconfigured fabric.peers entry
        pointing at a federation listener) is refused typed."""
        fe = ServingFrontend([tiny_engine(0)], fed_cfg())
        try:
            conn = dial(fe.federation_address, timeout_s=10.0,
                        max_frame_bytes=1 << 20, heartbeat_s=0.3,
                        name="test-bad-role")
            try:
                from deepspeed_tpu.serving.fabric.codec import CODEC_VERSION
                with pytest.raises(FabricError, match="federation_role:"):
                    conn.call("hello", {"codec_version": CODEC_VERSION,
                                        "replica_id": 0, "role": "mixed",
                                        "model_id": "default"},
                              timeout_s=10.0)
            finally:
                conn.close("test done")
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_stale_epoch_rejected_newer_supersedes(self):
        fe = ServingFrontend([tiny_engine(0)], fed_cfg())
        try:
            fab = fe.config.fabric
            addr = fe.federation_address
            first = FederationPeer(addr, fab, frontend_id="edge-X",
                                   epoch=100)
            first.connect()
            assert first.peer_id == fe._federation_id
            assert len(first.exports) == 1

            stale = FederationPeer(addr, fab, frontend_id="edge-X",
                                   epoch=50)
            with pytest.raises(FederationRefused, match="stale_epoch"):
                stale.connect()
            assert first.alive, "a refused zombie must not hurt the live peer"

            newer = FederationPeer(addr, fab, frontend_id="edge-X",
                                   epoch=200)
            newer.connect()
            deadline = time.monotonic() + 10
            while first.alive and time.monotonic() < deadline:
                time.sleep(0.05)
            assert not first.alive, \
                "a newer epoch must supersede the old connection"
            assert newer.alive
            newer.close()
            first.close()
        finally:
            fe.shutdown(drain=False, timeout=5)

    def test_no_transitive_reexport(self):
        """Adopted capacity is never re-exported: a frontend that itself
        adopted a peer's replica exports only its OWN locals — routing
        loops are impossible by construction."""
        fe_a = ServingFrontend([tiny_engine(0)], fed_cfg())
        fe_b = None
        try:
            fe_b = ServingFrontend([tiny_engine(1)],
                                   fed_cfg(peers=[fe_a.federation_address]))
            assert sum(1 for r in fe_b.router.replicas
                       if getattr(r, "is_federated", False)) == 1
            probe = FederationPeer(fe_b.federation_address,
                                   fe_b.config.fabric,
                                   frontend_id=derive_frontend_id(),
                                   epoch=1)
            probe.connect()
            assert len(probe.exports) == 1, \
                "B must export only its local replica, not A's"
            assert probe.exports[0]["export"] == 0
            probe.close()
        finally:
            if fe_b is not None:
                fe_b.shutdown(drain=False, timeout=5)
            fe_a.shutdown(drain=False, timeout=5)


# ========================================================== shared pool
class TestSharedPool:
    def test_two_frontend_parity_and_observability(self):
        ps = prompts(6, 31)
        ref = local_reference(ps, 8)
        fe_exp = ServingFrontend([tiny_engine(0)], fed_cfg())
        fe_adp = None
        try:
            fe_adp = ServingFrontend(
                [tiny_engine(1)], fed_cfg(peers=[fe_exp.federation_address]))
            got = run_fleet(fe_adp, ps, 8)
            assert got == ref, "federated pool broke greedy parity"
            snap = fe_adp.metrics_snapshot()
            assert snap["requests_federated"] >= 1
            kinds = [e["kind"] for e in fe_exp.journal.events()]
            assert "peer_connected" in kinds
            assert "replica_exported" in kinds
            # the ~1/s observability tick publishes the deduped peer
            # count on BOTH sides (adopter: dialed; exporter: adopted-by)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if (fe_adp.metrics_snapshot().get("federation_peers")
                        == 1.0
                        and fe_exp.metrics_snapshot()
                        .get("federation_peers") == 1.0):
                    break
                time.sleep(0.1)
            assert fe_adp.metrics_snapshot()["federation_peers"] == 1.0
            assert fe_exp.metrics_snapshot()["federation_peers"] == 1.0
        finally:
            if fe_adp is not None:
                fe_adp.shutdown(drain=False, timeout=5)
            fe_exp.shutdown(drain=False, timeout=5)

    def test_adopter_without_local_engines(self):
        """An edge frontend with NO local chips serves entirely off the
        shared pool — and the status stream's ``active_total`` reaches
        its capacity probe."""
        ps = prompts(3, 32, lo=8, hi=12)
        ref = local_reference(ps, 40)
        fe_exp = ServingFrontend([tiny_engine(0)], fed_cfg())
        fe_adp = None
        try:
            fe_adp = ServingFrontend(
                [], fed_cfg(peers=[fe_exp.federation_address]))
            handle = fe_adp.router.replica_by_id(federated_rid(fe_adp))
            hs = [fe_adp.submit(p, max_new_tokens=40) for p in ps]
            deadline = time.monotonic() + 60
            seen_total = 0
            while time.monotonic() < deadline and seen_total == 0:
                seen_total = handle._last_active_total
                time.sleep(0.01)
            assert fe_adp.wait_all(hs, timeout=120), [h.state for h in hs]
            got = [[ev.token for ev in h.drain()] for h in hs]
            assert got == ref
            assert seen_total >= 1, \
                "status stream never published the exporter's seat usage"
        finally:
            if fe_adp is not None:
                fe_adp.shutdown(drain=False, timeout=5)
            fe_exp.shutdown(drain=False, timeout=5)

    def test_capacity_accounting(self):
        """The adopter's capacity probe honors the exporter's TOTAL seat
        usage (shared with its local traffic) and the per-peer inflight
        cap."""
        fe_exp = ServingFrontend([tiny_engine(0)], fed_cfg())
        fe_adp = None
        try:
            fe_adp = ServingFrontend(
                [], fed_cfg(peers=[fe_exp.federation_address],
                            federation_extra={"peer_max_inflight": 2}))
            handle = fe_adp.router.replica_by_id(federated_rid(fe_adp))
            assert isinstance(handle, FederatedHandle)
            seats = handle.engine.config.max_ragged_sequence_count
            assert handle.has_capacity
            handle._last_active_total = seats
            assert not handle.has_capacity, \
                "exporter-side saturation must gate the adopter's probe"
            handle._last_active_total = 0
            assert handle.has_capacity

            class _BusyPeer:
                def inflight(self):
                    return 2
            real_peer = handle._peer
            handle._peer = _BusyPeer()
            assert not handle.has_capacity, \
                "peer_max_inflight must cap every mirror from one peer"
            handle._peer = real_peer
            assert handle.has_capacity
        finally:
            if fe_adp is not None:
                fe_adp.shutdown(drain=False, timeout=5)
            fe_exp.shutdown(drain=False, timeout=5)


# ============================================== cross-frontend failover
class TestCrossFrontendFailover:
    def _spawn_frontend(self, tmp_path):
        spec = {"model": MODEL_KW, "engine": ENGINE_KW, "seed": SEED,
                "n_replicas": 1,
                "serving": {"max_queue_depth": 64,
                            "fabric": {"enabled": True,
                                       "listen": "127.0.0.1:0",
                                       "heartbeat_s": 0.3,
                                       "federation": {"enabled": True}}}}
        spec_path = tmp_path / "frontend.json"
        spec_path.write_text(json.dumps(spec))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "serve_frontend.py"), "--spec", str(spec_path)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env)
        line = proc.stdout.readline()           # blocks until jax is up
        assert line.startswith("FEDERATION_LISTENING "), line
        return proc, line.split()[1]

    def test_kill_subprocess_frontend_mid_burst(self, tmp_path):
        """The real thing: a peer frontend in its own process, killed
        -9 mid-decode — every in-flight federated stream fails over to
        the adopter's local replica and resumes byte-losslessly."""
        ps = prompts(4, 33, lo=8, hi=12)
        # 4 concurrent seats x (prompt + 96) stays inside the engine's
        # 64x8-token KV pool — 160 here wedges the reference run dry
        ref = local_reference(ps, 96)
        proc, addr = self._spawn_frontend(tmp_path)
        fe = None
        try:
            fe = ServingFrontend(
                [tiny_engine(0)],
                fed_cfg(peers=[addr],
                        fault_tolerance={"enabled": True, "max_retries": 3,
                                         "restart_backoff_s": 0.1}))
            fed_rid = federated_rid(fe)
            hs = [fe.submit(p, max_new_tokens=96) for p in ps]
            deadline = time.monotonic() + 90
            live = False
            while time.monotonic() < deadline and not live:
                live = any(h._req.replica_id == fed_rid
                           and h._req.n_generated >= 2 for h in hs)
                time.sleep(0.002)
            assert live, "no stream ever ran on the federated replica"
            proc.kill()                         # SIGKILL: no goodbye
            assert fe.wait_all(hs, timeout=180), [h.state for h in hs]
            got = [[ev.token for ev in h.drain()] for h in hs]
            snap = fe.metrics_snapshot()
        finally:
            if fe is not None:
                fe.shutdown(drain=False, timeout=5)
            proc.kill()
            proc.wait(timeout=10)
        assert got == ref, "cross-frontend failover broke byte parity"
        assert snap["requests_failed_over"] >= 1


# =========================================================== evacuation
class TestFederatedEvacuation:
    def test_local_drain_onto_peer(self):
        """Removing the adopter's local replica drains its in-flight
        streams onto the PEER's exported replica — the autoscaler's
        drain-onto-peers shutdown path, byte-lossless."""
        ps = prompts(2, 34, lo=8, hi=12)
        ref = local_reference(ps, 160)
        fe_exp = ServingFrontend([tiny_engine(0)], fed_cfg())
        fe_adp = None
        try:
            fe_adp = ServingFrontend(
                [tiny_engine(1)],
                fed_cfg(peers=[fe_exp.federation_address],
                        fault_tolerance={"enabled": True,
                                         "max_retries": 3}))
            local_rid = next(r.replica_id for r in fe_adp.router.replicas
                             if not getattr(r, "is_remote", False))
            hs = [fe_adp.submit(p, max_new_tokens=160) for p in ps]
            deadline = time.monotonic() + 60
            live = False
            while time.monotonic() < deadline and not live:
                live = any(h._req.replica_id == local_rid
                           and h._req.n_generated >= 2 for h in hs)
                time.sleep(0.002)
            assert live, "no stream ever ran on the local replica"
            assert fe_adp.remove_replica(local_rid, timeout_s=30.0)
            assert fe_adp.wait_all(hs, timeout=120), [h.state for h in hs]
            got = [[ev.token for ev in h.drain()] for h in hs]
            snap = fe_adp.metrics_snapshot()
        finally:
            if fe_adp is not None:
                fe_adp.shutdown(drain=False, timeout=5)
            fe_exp.shutdown(drain=False, timeout=5)
        assert got == ref, "drain-onto-peer broke byte parity"
        assert snap["requests_evacuated"] >= 1

    def test_remove_federated_member_requeues_mirrors(self):
        """Removing a FEDERATED member evacuates only the adopter's
        mirrors (the exporter's shared replica keeps serving its own
        traffic) and the mirrors resume locally, byte-lossless."""
        ps = prompts(2, 35, lo=8, hi=12)
        ref = local_reference(ps, 160)
        fe_exp = ServingFrontend([tiny_engine(0)], fed_cfg())
        fe_adp = None
        try:
            fe_adp = ServingFrontend(
                [tiny_engine(1)],
                fed_cfg(peers=[fe_exp.federation_address],
                        fault_tolerance={"enabled": True,
                                         "max_retries": 3}))
            fed_rid = federated_rid(fe_adp)
            hs = [fe_adp.submit(p, max_new_tokens=160) for p in ps]
            deadline = time.monotonic() + 60
            live = False
            while time.monotonic() < deadline and not live:
                live = any(h._req.replica_id == fed_rid
                           and h._req.n_generated >= 2 for h in hs)
                time.sleep(0.002)
            assert live, "no stream ever ran on the federated replica"
            assert fe_adp.remove_replica(fed_rid, timeout_s=30.0)
            assert fe_adp.wait_all(hs, timeout=120), [h.state for h in hs]
            got = [[ev.token for ev in h.drain()] for h in hs]
        finally:
            if fe_adp is not None:
                fe_adp.shutdown(drain=False, timeout=5)
            fe_exp.shutdown(drain=False, timeout=5)
        assert got == ref, "federated-member removal broke byte parity"


# ====================================================== disabled parity
class TestDisabledParity:
    def test_disabled_is_single_frontend_stack(self):
        """``federation`` absent = byte-for-byte the historical fabric
        stack: no identity, no listener, no federation observability."""
        ps = prompts(6, 36)
        plain = ServingFrontend([tiny_engine(0)],
                                ServingConfig(max_queue_depth=64))
        try:
            ref = run_fleet(plain, ps, 8)
        finally:
            plain.shutdown(drain=False, timeout=5)

        fe = ServingFrontend(
            [tiny_engine(1)],
            ServingConfig(max_queue_depth=64, fabric={"enabled": True}))
        try:
            assert fe._federation is None
            assert fe._federation_server is None
            assert fe.federation_address is None
            assert fe._federation_peers == []
            got = run_fleet(fe, ps, 8)
            snap = fe.metrics_snapshot()
            kinds = {e["kind"] for e in fe.journal.events()}
        finally:
            fe.shutdown(drain=False, timeout=5)
        assert got == ref, "disabled federation changed tokens"
        assert snap.get("requests_federated", 0) == 0
        assert not kinds & {"peer_connected", "peer_lost",
                            "replica_exported"}
