"""Fleet chaos engineering (ISSUE 20, docs/SERVING.md "Fleet chaos
engineering").

The network fault injector (serving/fabric/chaos.py) as a unit —
seeded determinism, the fired ledger, link matching, every fault
pipeline — plus the machinery it exists to drill: gray-failure
quarantine (rolling RPC-latency scoring → QUARANTINED → probe
re-admission → escalation), its composition with the autoscaler,
affinity routing and federation, reconnect-storm protection
(full-jitter backoff + the dial-concurrency gate), CRC frame-sealing
negotiation, and partition-tolerant federation seat leases
(``lease_timeout_s`` expiry, ``peer_partition`` journaling, heal =
exactly-once re-adoption). Transport-level chaos edges live in
tests/test_fabric.py (TestTransportChaosEdges)."""

import random
import socket
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.serving import (RequestState, ServingConfig,
                                   ServingFrontend)
from deepspeed_tpu.serving.fabric import chaos as fchaos
from deepspeed_tpu.serving.fabric import codec as fcodec
from deepspeed_tpu.serving.fabric import federation as ffederation
from deepspeed_tpu.serving.fabric import transport as ftransport
from deepspeed_tpu.serving.fabric.chaos import (ChaosKill,
                                                NetworkFaultInjector)
from deepspeed_tpu.serving.fabric.server import ReplicaServer
from deepspeed_tpu.serving.replica import ReplicaState
from deepspeed_tpu.utils.restart import RestartPolicy

VOCAB = 128
MODEL_KW = dict(vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=2, max_seq_len=256, norm="rmsnorm",
                activation="silu", position="rope")
ENGINE_KW = dict(max_ragged_batch_size=128, max_ragged_sequence_count=4,
                 max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
                 max_tracked_sequences=32)
SEED = 0

_model = None
_params = None


def tiny_engine(i=0, **cfg_over):
    global _model, _params
    import jax

    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    if _model is None:
        _model = CausalLM(TransformerConfig(**MODEL_KW))
        _params = _model.init(jax.random.PRNGKey(SEED))
    base = dict(ENGINE_KW)
    base.update(cfg_over)
    return InferenceEngineV2(_model, params=_params,
                             config=RaggedInferenceEngineConfig(**base))


def prompts(n, seed, lo=8, hi=24):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(length)).tolist()
            for length in rng.integers(lo, hi, size=n)]


def run_fleet(fe, ps, max_new, timeout=300):
    hs = [fe.submit(p, max_new_tokens=max_new) for p in ps]
    assert fe.wait_all(hs, timeout=timeout), [h.state for h in hs]
    return [[ev.token for ev in h.drain()] for h in hs]


def local_reference(ps, max_new, n_replicas=1):
    fe = ServingFrontend([tiny_engine(i) for i in range(n_replicas)],
                         ServingConfig(max_queue_depth=64))
    try:
        return run_fleet(fe, ps, max_new)
    finally:
        fe.shutdown(drain=False, timeout=5)


class _Servers:
    def __init__(self, n, server_config=None, heartbeat_s=0.3, **eng_over):
        self.servers = [
            ReplicaServer(lambda i=i: tiny_engine(i, **eng_over),
                          server_config or ServingConfig(),
                          listen="127.0.0.1:0", replica_id=i,
                          heartbeat_s=heartbeat_s)
            for i in range(n)]
        for s in self.servers:
            s.start()
        self.peers = [f"127.0.0.1:{s.port}" for s in self.servers]

    def stop(self):
        for s in self.servers:
            s.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def fabric_cfg(peers, heartbeat_s=0.3, fabric_extra=None, **extra):
    fab = {"enabled": True, "peers": list(peers),
           "heartbeat_s": heartbeat_s, "rpc_timeout_s": 60.0}
    fab.update(fabric_extra or {})
    return ServingConfig(max_queue_depth=64, fabric=fab, **extra)


QUAR = {"enabled": True, "rpc_slow_s": 0.5, "window": 8,
        "min_samples": 4, "slow_fraction": 0.75,
        "probe_backoff_s": 30.0, "probe_backoff_max_s": 60.0,
        "escalate_quarantines": 10, "escalate_window_s": 120.0}


def fed_cfg(peers=(), heartbeat_s=0.2, federation_extra=None,
            fabric_extra=None, **extra):
    fed = {"enabled": True, "peers": list(peers)}
    fed.update(federation_extra or {})
    fab = {"enabled": True, "listen": "127.0.0.1:0",
           "heartbeat_s": heartbeat_s, "rpc_timeout_s": 60.0,
           "federation": fed}
    fab.update(fabric_extra or {})
    return ServingConfig(max_queue_depth=64, fabric=fab, **extra)


class _FakeSock:
    """Collects sendall bytes — enough socket for ChaosLink.send."""

    def __init__(self):
        self.data = b""

    def sendall(self, b):
        self.data += b


# =========================================================== injector unit
class TestInjectorUnit:
    def test_unknown_kind_and_bad_fields_refused(self):
        with pytest.raises(ValueError, match="unknown kind"):
            NetworkFaultInjector([{"kind": "gremlin"}])
        with pytest.raises(ValueError, match="dir"):
            NetworkFaultInjector([{"kind": "latency", "dir": "sideways"}])
        with pytest.raises(ValueError, match="where"):
            NetworkFaultInjector([{"kind": "corrupt",
                                   "where": "trailer"}])

    def test_attach_link_matching(self):
        inj = NetworkFaultInjector(
            [{"kind": "latency", "link": "fabric-r0", "delay_s": 0.01},
             {"kind": "corrupt", "link": "federation-*"}])
        assert inj.attach("fabric-r0") is not None
        assert inj.attach("fabric-r1") is None
        assert inj.attach("federation-peer-x") is not None
        assert inj.attach("unrelated") is None
        # module-level attach with nothing installed: zero interposition
        assert fchaos.installed() is None
        assert fchaos.attach("fabric-r0") is None

    def test_at_frame_range_draw_is_seeded(self):
        sched = [{"kind": "drop_conn", "at_frame_range": [10, 500]}]
        a = NetworkFaultInjector(sched, seed=7)
        b = NetworkFaultInjector(sched, seed=7)
        assert a.events[0].at_frame == b.events[0].at_frame
        assert 10 <= a.events[0].at_frame <= 500

    def test_blackhole_count_cap_and_ledger(self):
        inj = NetworkFaultInjector(
            [{"kind": "blackhole", "link": "l", "dir": "rx",
              "count": 2}])
        link = inj.attach("l")
        assert link.recv(b"one") == []
        assert link.recv(b"two") == []
        assert link.recv(b"three") == [b"three"]   # count exhausted
        hits = inj.fired("blackhole", "l")
        assert [h[3] for h in hits] == [0, 1]      # frame indices
        assert all(h[2] == "rx" for h in hits)

    def test_at_frame_arms_late(self):
        inj = NetworkFaultInjector(
            [{"kind": "blackhole", "link": "l", "dir": "rx",
              "at_frame": 2}])
        link = inj.attach("l")
        assert link.recv(b"a") == [b"a"]
        assert link.recv(b"b") == [b"b"]
        assert link.recv(b"c") == []               # frame 2 onward

    def test_duplicate_and_reorder_one_way(self):
        inj = NetworkFaultInjector(
            [{"kind": "duplicate", "link": "dup", "dir": "rx",
              "count": 1}])
        link = inj.attach("dup")
        assert link.recv(b"x") == [b"x", b"x"]
        assert link.recv(b"y") == [b"y"]
        inj2 = NetworkFaultInjector(
            [{"kind": "reorder", "link": "ro", "dir": "rx",
              "count": 1}])
        ro = inj2.attach("ro")
        assert ro.recv(b"first") == []             # held
        assert ro.recv(b"second") == [b"second", b"first"]

    def test_corrupt_is_seeded_deterministic(self):
        body = fcodec.encode_frame({"t": "ev",
                                    "a": np.arange(32, dtype=np.int8)})
        outs = []
        for _ in range(2):
            inj = NetworkFaultInjector(
                [{"kind": "corrupt", "link": "c", "dir": "rx"}], seed=3)
            outs.append(inj.attach("c").recv(bytes(body))[0])
        assert outs[0] == outs[1], "same seed must corrupt identically"
        assert outs[0] != body

    def test_drop_conn_paths(self):
        inj = NetworkFaultInjector(
            [{"kind": "drop_conn", "link": "k", "dir": "rx",
              "at_frame": 0}])
        with pytest.raises(ChaosKill):
            inj.attach("k").recv(b"x")
        inj2 = NetworkFaultInjector(
            [{"kind": "drop_conn", "link": "k", "dir": "tx",
              "partial_bytes": 2}])
        sock = _FakeSock()
        with pytest.raises(ChaosKill):
            inj2.attach("k").send(sock, b"abcdef")
        # length prefix promises 6 bytes, only 2 ever arrive
        assert sock.data == b"\x00\x00\x00\x06ab"

    def test_hit_state_shared_across_reconnects(self):
        """A count-capped event must not re-fire on every re-dial of
        the link — hit-state lives on the injector, not the shim."""
        inj = NetworkFaultInjector(
            [{"kind": "blackhole", "link": "l", "dir": "rx",
              "count": 1}])
        first = inj.attach("l")
        assert first.recv(b"a") == []
        second = inj.attach("l")                   # "reconnect"
        assert second.recv(b"b") == [b"b"]


# ========================================================= reconnect storm
class TestReconnectStorm:
    def test_full_jitter_spreads_over_whole_interval(self):
        pol = RestartPolicy(backoff_s=1.0, backoff_max_s=8.0, jitter=0.2,
                            max_failures_in_window=100, window_s=1e6,
                            rng=random.Random(42), full_jitter=True)
        ref = random.Random(42)
        t = 0.0
        for n in range(1, 8):
            _, backoff = pol.record_failure(t)
            raw = min(1.0 * (2 ** (n - 1)), 8.0)
            assert backoff == raw * ref.random()
            assert 0.0 <= backoff <= raw
            t += 10.0

    def test_proportional_jitter_unchanged_by_default(self):
        pol = RestartPolicy(backoff_s=1.0, backoff_max_s=8.0, jitter=0.2,
                            max_failures_in_window=100, window_s=1e6,
                            rng=random.Random(42))
        ref = random.Random(42)
        _, backoff = pol.record_failure(0.0)
        assert backoff == 1.0 * (1.0 + 0.2 * ref.random())
        assert 1.0 <= backoff <= 1.2

    def test_full_jitter_is_seeded_deterministic(self):
        mk = lambda: RestartPolicy(0.5, 30.0, 0.2, 100, 1e6,
                                   random.Random(7), full_jitter=True)
        a, b = mk(), mk()
        seq_a = [a.record_failure(float(i))[1] for i in range(6)]
        seq_b = [b.record_failure(float(i))[1] for i in range(6)]
        assert seq_a == seq_b

    def test_remote_handle_uses_full_jitter(self):
        from deepspeed_tpu.serving.fabric.remote import RemoteHandle

        cfg = fabric_cfg(["127.0.0.1:1"]).fabric
        h = RemoteHandle(0, "127.0.0.1:1", cfg)
        assert h._restart.full_jitter, \
            "fabric re-dials must use full-jitter backoff"

    def test_dial_gate_caps_concurrency(self, monkeypatch):
        active, peak = [0], [0]
        lk = threading.Lock()
        held = []
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(16)
        port = lst.getsockname()[1]
        real_create = socket.create_connection

        def drain_accepts():
            while True:
                try:
                    held.append(lst.accept()[0])
                except OSError:
                    return

        acceptor = threading.Thread(target=drain_accepts, daemon=True)
        acceptor.start()

        def fake_create_connection(addr, timeout=None):
            with lk:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.15)
            with lk:
                active[0] -= 1
            return real_create(("127.0.0.1", port), timeout=timeout)

        monkeypatch.setattr(ftransport.socket, "create_connection",
                            fake_create_connection)
        old = ftransport.DIAL_MAX_CONCURRENT
        ftransport.set_dial_concurrency(2)
        conns = []
        try:
            def one():
                conns.append(ftransport.dial("127.0.0.1:1"))

            threads = [threading.Thread(target=one) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert peak[0] == 2, \
                f"dial gate leaked: {peak[0]} concurrent connects"
            assert len(conns) == 6
        finally:
            ftransport.set_dial_concurrency(old)
            for c in conns:
                c.close()
            lst.close()
            acceptor.join(timeout=5)
            for s in held:
                s.close()


# ========================================================= CRC negotiation
class TestCrcNegotiation:
    def test_crc_on_by_default_with_parity(self):
        ps = prompts(3, 50)
        ref = local_reference(ps, 5)
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                h = fe.router.replicas[0]
                assert h._conn.crc_tx and h._conn.crc_rx, \
                    "frame CRC must negotiate on between new peers"
                got = run_fleet(fe, ps, 5)
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref, "CRC sealing broke greedy byte-parity"

    def test_frame_crc_false_is_v1_wire(self):
        ps = prompts(3, 51)
        ref = local_reference(ps, 5)
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, fabric_extra={"frame_crc": False}))
            try:
                h = fe.router.replicas[0]
                assert not h._conn.crc_tx and not h._conn.crc_rx, \
                    "frame_crc: false must never advertise sealing"
                got = run_fleet(fe, ps, 5)
            finally:
                fe.shutdown(drain=False, timeout=5)
        assert got == ref


# ============================================================== quarantine
class TestQuarantine:
    def test_slow_rpcs_fire_quarantine_then_probe_readmits(self):
        with _Servers(1, heartbeat_s=0.2) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, heartbeat_s=0.2,
                fabric_extra={"quarantine": dict(QUAR,
                                                 probe_backoff_s=0.2,
                                                 probe_backoff_max_s=1.0)}))
            try:
                h = fe.router.replicas[0]
                assert h._qcfg is not None
                for _ in range(4):
                    h._q_observe(1.0, False)       # 4/4 slow ≥ 75%
                assert h.state == ReplicaState.QUARANTINED
                assert not h.accepting
                assert fe.journal.count("replica_quarantined") == 1
                # the gauge reflects it on the next router tick
                deadline = time.monotonic() + 10
                while fe.metrics_snapshot().get(
                        "replicas_quarantined", 0) != 1 \
                        and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert fe.metrics_snapshot()["replicas_quarantined"] == 1
                # the server answers probes fast → re-admission
                deadline = time.monotonic() + 30
                while h.state == ReplicaState.QUARANTINED \
                        and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert h.state == ReplicaState.HEALTHY, \
                    "probe never re-admitted a healthy peer"
                assert fe.journal.count("replica_readmitted") == 1
                ev = fe.journal.events(kinds=("replica_readmitted",))[0]
                assert ev["detail"]["quarantined_s"] >= 0.0
                # and it serves again, byte-exact
                ps = prompts(2, 52)
                assert run_fleet(fe, ps, 4) == local_reference(ps, 4)
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_repeated_quarantine_escalates_to_dead(self):
        with _Servers(1, heartbeat_s=0.2) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, heartbeat_s=0.2,
                fabric_extra={"quarantine": dict(
                    QUAR, escalate_quarantines=2)}))
            try:
                h = fe.router.replicas[0]
                h._quarantine("test gray failure")
                assert h.state == ReplicaState.QUARANTINED
                h._readmit()
                assert h.state == ReplicaState.HEALTHY
                h._quarantine("test gray failure again")
                assert h.state == ReplicaState.DEAD, \
                    "2nd quarantine in the window must escalate"
                assert fe.journal.count("replica_quarantined") == 1
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_min_samples_and_fast_rpcs_never_fire(self):
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, fabric_extra={"quarantine": QUAR}))
            try:
                h = fe.router.replicas[0]
                # 2 slow samples can never satisfy slow_fraction=0.75
                # even when live status RPCs pad the window with fast
                # samples (min_samples=4 → best case 2/4 = 50%)
                for _ in range(2):
                    h._q_observe(1.0, False)
                assert h.state == ReplicaState.HEALTHY
                for _ in range(20):                # fast calls
                    h._q_observe(0.001, False)
                assert h.state == ReplicaState.HEALTHY
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_router_holds_not_fails_on_quarantined_capacity(self):
        """A QUARANTINED replica is sick, not gone: with no other
        capacity for the model, fresh work WAITS for re-admission
        instead of failing undispatchable."""
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, fabric_extra={"quarantine": QUAR}))
            try:
                h = fe.router.replicas[0]
                h._quarantine("test hold")
                assert h.state == ReplicaState.QUARANTINED
                hs = [fe.submit(p, max_new_tokens=4)
                      for p in prompts(2, 53)]
                time.sleep(1.0)
                assert all(x.state == RequestState.QUEUED for x in hs), \
                    [x.state for x in hs]
                h._readmit()
                assert fe.wait_all(hs, timeout=60), [x.state for x in hs]
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_quarantine_disabled_is_inert(self):
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(srv.peers))
            try:
                h = fe.router.replicas[0]
                assert h._qcfg is None
                for _ in range(50):
                    h._q_observe(10.0, True)
                assert h.state == ReplicaState.HEALTHY
            finally:
                fe.shutdown(drain=False, timeout=5)


# ================================================== quarantine composition
class TestQuarantineComposition:
    def test_autoscaler_never_shrinks_quarantined_victim(self):
        """A quarantined replica holding streams is invisible to the
        shrink pick — it is not accepting, and victims come only from
        accepting replicas."""
        from deepspeed_tpu.serving.autoscaler import FleetController

        with _Servers(2) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers, fabric_extra={"quarantine": QUAR}))
            try:
                victim = fe.router.replicas[0]
                victim._quarantine("test")
                signals = fe.fleet_signals()
                info = {r.replica_id: r for r in signals.replicas}
                assert not info[victim.replica_id].accepting
                assert not info[victim.replica_id].parked
                ctl = FleetController.__new__(FleetController)
                chosen = ctl._shrink_victim(signals)
                assert chosen != victim.replica_id
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_affinity_digest_from_quarantined_earns_no_steering(self):
        """rid0 holds the whole prompt's digest; while QUARANTINED its
        digest must not pull the request — it routes to the digest-less
        healthy replica instead."""
        from deepspeed_tpu.serving.affinity import chain_hashes

        p = prompts(1, 54, lo=32, hi=33)[0]
        with _Servers(2) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers,
                fabric_extra={"quarantine": QUAR},
                affinity={"enabled": True, "refresh_interval_s": 1e6}))
            try:
                r0, r1 = fe.router.replicas
                fe._affinity._digests = {
                    r0.replica_id: frozenset(chain_hashes(
                        p, ENGINE_KW["kv_block_size"]))}
                h = fe.submit(p, max_new_tokens=4)
                assert fe.wait_all([h], timeout=60)
                assert h._req.replica_id == r0.replica_id, \
                    "sanity: affinity should steer to the digest holder"
                r0._quarantine("test")
                h2 = fe.submit(p, max_new_tokens=4)
                assert fe.wait_all([h2], timeout=60)
                assert h2._req.replica_id == r1.replica_id, \
                    "a quarantined replica's digest still earned steering"
            finally:
                fe.shutdown(drain=False, timeout=5)

    def test_quarantined_federated_member_takes_no_fresh_work(self):
        """Fresh work stays local while the federated member is
        quarantined (its seats on the exporter serve nobody new), and
        re-admission restores it."""
        fe_a = ServingFrontend(
            [tiny_engine(0)], fed_cfg(fabric_extra={"quarantine": QUAR}))
        fe_b = None
        try:
            fe_b = ServingFrontend(
                [tiny_engine(1)],
                fed_cfg(peers=[fe_a.federation_address],
                        fabric_extra={"quarantine": QUAR}))
            fed = next(r for r in fe_b.router.replicas
                       if getattr(r, "is_federated", False))
            assert fed._qcfg is not None
            fed._quarantine("test gray peer")
            assert fed.state == ReplicaState.QUARANTINED
            before = fe_b.metrics_snapshot().get("requests_federated", 0)
            ps = prompts(4, 55)
            got = run_fleet(fe_b, ps, 4)
            after = fe_b.metrics_snapshot().get("requests_federated", 0)
            assert after == before, \
                "fresh work crossed to a quarantined federated member"
            assert got == local_reference(ps, 4)
            fed._readmit()
            assert fed.accepting
        finally:
            if fe_b is not None:
                fe_b.shutdown(drain=False, timeout=5)
            fe_a.shutdown(drain=False, timeout=5)


# ======================================================== federation lease
class TestFederationLease:
    def test_partition_expires_lease_and_heals_exactly_once(
            self, monkeypatch):
        """Silence the adopter→exporter direction (asymmetric
        partition): the exporter journals ``peer_partition`` once,
        expires the seat lease (``lease_expired`` +
        ``federation_leases_expired``), and closes the export channel —
        whereupon the adopter's supervisor re-adopts over fresh
        channels exactly once."""
        monkeypatch.setattr(ffederation, "STALE_FLOOR_S", 0.5)
        fe_a = ServingFrontend(
            [tiny_engine(0)],
            fed_cfg(heartbeat_s=0.2,
                    federation_extra={"lease_timeout_s": 1.0}))
        fe_b = None
        muted = []
        try:
            fe_b = ServingFrontend(
                [tiny_engine(1)],
                fed_cfg(peers=[fe_a.federation_address], heartbeat_s=0.2,
                        fault_tolerance={"enabled": True,
                                         "max_retries": 3,
                                         "restart_backoff_s": 0.1,
                                         "max_restarts_in_window": 50}))
            assert any(getattr(r, "is_federated", False)
                       for r in fe_b.router.replicas)
            exported_before = fe_a.journal.count("replica_exported")
            assert exported_before >= 1

            # partition: drop every frame crossing the link (sends
            # become no-ops on both sides' current connections; the
            # supervisor's RE-DIAL builds fresh, unmuted connections —
            # that IS the heal)
            conns = []
            srv = fe_a._federation_server
            with srv._lock:
                conns += [c.conn for c in srv._channels
                          if c.conn is not None]
            for peer in fe_b._federation_peers:
                if peer._conn is not None:
                    conns.append(peer._conn)
            for r in fe_b.router.replicas:
                if getattr(r, "is_federated", False) \
                        and r._conn is not None:
                    conns.append(r._conn)
            for c in conns:
                muted.append((c, c.send))
                c.send = lambda msg: None

            deadline = time.monotonic() + 30
            while (fe_a.journal.count("lease_expired") < 1
                   or fe_a.journal.count("peer_partition") < 1) \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert fe_a.journal.count("peer_partition") >= 1, \
                "silent boot channel never journaled the partition"
            assert fe_a.journal.count("lease_expired") == 1
            assert fe_a.metrics_snapshot()[
                "federation_leases_expired"] == 1
            ev = fe_a.journal.events(kinds=("lease_expired",))[0]
            assert ev["detail"]["idle_s"] > 1.0

            # heal: the adopter re-dials and the exporter re-binds the
            # replica to a fresh export channel — exactly once
            deadline = time.monotonic() + 30
            while fe_a.journal.count("replica_exported") \
                    < exported_before + 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert fe_a.journal.count("replica_exported") \
                == exported_before + 1, "re-adoption never happened"
            time.sleep(1.5)         # settle: no duplicate re-adoption
            assert fe_a.journal.count("replica_exported") \
                == exported_before + 1, "re-adoption was not exactly-once"
            assert fe_a.journal.count("lease_expired") == 1, \
                "a healed link kept expiring leases"

            # un-mute survivors and prove the pool serves
            for c, orig in muted:
                c.send = orig
            muted = []
            deadline = time.monotonic() + 30
            while not any(getattr(r, "is_federated", False)
                          and r.accepting
                          for r in fe_b.router.replicas) \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            ps = prompts(3, 56)
            assert run_fleet(fe_b, ps, 4) == local_reference(ps, 4)
        finally:
            for c, orig in muted:
                c.send = orig
            if fe_b is not None:
                fe_b.shutdown(drain=False, timeout=5)
            fe_a.shutdown(drain=False, timeout=5)


# ======================================================= chaos via config
class TestChaosConfig:
    def test_schedule_through_config_with_parity_and_uninstall(self):
        ps = prompts(3, 57)
        ref = local_reference(ps, 5)
        with _Servers(1) as srv:
            fe = ServingFrontend([], fabric_cfg(
                srv.peers,
                chaos={"enabled": True, "seed": 1, "schedule": [
                    {"kind": "latency", "link": "fabric-r*",
                     "delay_s": 0.01, "jitter_s": 0.01,
                     "duration_s": 30.0}]}))
            try:
                assert fchaos.installed() is fe.net_chaos
                h = fe.router.replicas[0]
                assert h._conn._chaos is not None
                got = run_fleet(fe, ps, 5)
                assert fe.net_chaos.fired("latency"), \
                    "scheduled latency never fired"
            finally:
                fe.shutdown(drain=False, timeout=5)
            assert fchaos.installed() is None, \
                "shutdown must uninstall the frontend's own injector"
        assert got == ref, "latency chaos broke greedy byte-parity"

    def test_disabled_chaos_builds_nothing(self):
        cfg = ServingConfig()
        assert cfg.chaos.build_injector() is None
        fe = ServingFrontend([tiny_engine()], cfg)
        try:
            assert fe.net_chaos is None
            assert fchaos.installed() is None
        finally:
            fe.shutdown(drain=False, timeout=5)
