"""Tiered KV memory (ISSUE 10): host-RAM/disk spillover for the prefix
cache with restore on match (docs/SERVING.md "KV tiering").

Invariants under test: spill/restore byte round-trips exactly (fp32,
bf16, int8 + scale planes), referenced blocks are never spilled, restored
blocks re-enter the index under their original ``(parent_hash, tokens)``
key, LRU ordering inside the tier (host demotes/drops oldest first, disk
drops oldest first), disk corruption reads back as a miss (re-prefill —
never a crash), restores compose with cancel/deadline/replica-death, and
the disabled path is byte-for-byte the tier-less stack."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.kv_tier import TieredKVStore
from deepspeed_tpu.inference.v2.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.inference.v2.testing import greedy_generate
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving.config import KVTierConfig

VOCAB = 128
BS = 8          # kv block size used throughout


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2, num_heads=2,
                            max_seq_len=128, norm="rmsnorm",
                            activation="silu", position="rope")
    model = CausalLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def make_engine(model, params, tier=True, kv_blocks=14, quant=False,
                qdtype="int8", dtype=None, host_bytes=64 << 20,
                disk_path=None, disk_bytes=0, prefix=True, max_seqs=4):
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=max_seqs,
        max_chunk_tokens=32, kv_blocks=kv_blocks, kv_block_size=BS,
        max_tracked_sequences=64, enable_prefix_cache=prefix,
        kv_quant_enabled=quant, kv_quant_dtype=qdtype)
    eng = InferenceEngineV2(model, params=params, config=vcfg)
    if tier:
        eng.configure_kv_tier(True, host_bytes=host_bytes,
                              disk_path=disk_path, disk_bytes=disk_bytes)
    return eng


def rand_prompt(rng, n):
    return rng.integers(0, VOCAB, size=n).tolist()


def shared_prefix_reqs(rng, k_prompts=4, n_req=12, sys_len=32, tail=6):
    sys_prompts = [rand_prompt(rng, sys_len) for _ in range(k_prompts)]
    return [sys_prompts[i % k_prompts] + rand_prompt(rng, tail)
            for i in range(n_req)]


def block_slabs(eng, block):
    """One block's slab content from every pool tensor, materialized."""
    return {name: np.asarray(pool[:, block])
            for name, pool in eng.state_manager.kv_cache.items()}


# ------------------------------------------------------- store unit tests
def entry(rng, nbytes=1024, dtype=np.float32):
    n = nbytes // np.dtype(dtype).itemsize
    return {"k": rng.normal(size=n).astype(dtype),
            "v": rng.normal(size=n).astype(dtype)}


def test_store_put_get_roundtrip_and_pop():
    rng = np.random.default_rng(0)
    st = TieredKVStore(host_max_bytes=1 << 20)
    e = entry(rng)
    assert st.put(("h", (1, 2)), e)
    got = st.get(("h", (1, 2)))
    np.testing.assert_array_equal(got["k"], e["k"])
    np.testing.assert_array_equal(got["v"], e["v"])
    # get pops: the device pool is the authority again
    assert st.get(("h", (1, 2))) is None
    assert st.stats["hits"] == 1 and st.stats["misses"] == 1
    assert st.host_bytes == 0


def test_store_host_lru_drops_oldest_without_disk():
    rng = np.random.default_rng(1)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=2 * nbytes)
    for i in range(3):
        assert st.put((i,), entry(rng))
    host, disk = st.lru_keys()
    assert host == [(1,), (2,)] and disk == []   # oldest dropped first
    assert st.stats["dropped"] == 1
    assert st.get((0,)) is None


def test_store_lru_touch_on_overwrite():
    rng = np.random.default_rng(2)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=2 * nbytes)
    st.put((0,), entry(rng))
    st.put((1,), entry(rng))
    st.put((0,), entry(rng))            # overwrite refreshes recency
    st.put((2,), entry(rng))            # now (1,) is the LRU victim
    host, _ = st.lru_keys()
    assert host == [(0,), (2,)]


def test_store_demotes_to_disk_and_restores(tmp_path):
    rng = np.random.default_rng(3)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=nbytes,
                       disk_path=str(tmp_path), disk_max_bytes=10 * nbytes)
    e0, e1 = entry(rng), entry(rng)
    st.put((0,), e0)
    st.put((1,), e1)                    # (0,) demotes to disk
    host, disk = st.lru_keys()
    assert host == [(1,)] and disk == [(0,)]
    assert st.stats["demoted"] == 1 and st.disk_bytes > 0
    got = st.get((0,))                  # disk round-trip, CRC-checked
    np.testing.assert_array_equal(got["k"], e0["k"])
    np.testing.assert_array_equal(got["v"], e0["v"])
    assert st.disk_bytes == 0


def test_store_disk_lru_bound_drops_oldest(tmp_path):
    rng = np.random.default_rng(4)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=nbytes,
                       disk_path=str(tmp_path), disk_max_bytes=2 * nbytes)
    for i in range(4):                  # 1 host + 2 disk fit; 1 drops
        st.put((i,), entry(rng))
    host, disk = st.lru_keys()
    assert host == [(3,)] and disk == [(1,), (2,)]
    assert st.stats["dropped"] == 1
    assert st.get((0,)) is None


def test_store_failed_demotion_leaves_no_partial_file(tmp_path):
    """A demotion whose disk write fails must remove any partial file —
    it sits outside disk_bytes accounting and the live process's sweep
    never touches it (an intermittent-I/O server would leak forever)."""
    rng = np.random.default_rng(65)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=nbytes,
                       disk_path=str(tmp_path), disk_max_bytes=1 << 20)

    def boom(key, arr):
        with open(os.path.join(tmp_path, f"{key}.swp"), "wb") as fh:
            fh.write(b"partial")            # torn write, then failure
        raise IOError("ENOSPC")

    st._swapper.swap_out = boom
    st.put((0,), entry(rng))
    st.put((1,), entry(rng))                # (0,) demotes -> write fails
    assert st.stats["dropped"] == 1
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".swp")]
    st.close()


def test_restore_lookahead_capped_at_pool_budget(model_and_params):
    """A spilled chain longer than the pool's free+evictable capacity
    must not be popped (and disk-churned) past what can actually be
    restored — the lookahead caps BEFORE touching the tier."""
    model, params = model_and_params
    rng = np.random.default_rng(66)
    eng = make_engine(model, params, kv_blocks=8)
    sm = eng.state_manager
    prompt = rand_prompt(rng, 6 * BS + 2)   # 6 full blocks
    eng.put([1], [prompt[:4 * BS]])
    eng.put([1], [prompt[4 * BS:]])
    eng.flush(1)
    assert sm._evict(6) == 6                # all 6 spilled
    # occupy the pool so only 2 blocks can come back
    p_b = rand_prompt(rng, 5 * BS)
    eng.put([2], [p_b[:32]])
    eng.put([2], [p_b[32:]])
    free = sm.allocator.free_blocks
    assert free < 6
    hits0 = eng.tier_stats()["hits"]
    matched = eng.match_prefix(3, prompt)
    assert matched == free * BS             # restored what fit
    # only the restorable prefix was popped: no pop-then-readmit churn
    assert eng.tier_stats()["hits"] - hits0 == free
    assert len(sm._tier) == 6 - free        # tail untouched in the tier
    eng.flush(2)
    eng.flush(3)


def test_store_disk_corruption_is_miss(tmp_path):
    rng = np.random.default_rng(5)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=nbytes,
                       disk_path=str(tmp_path), disk_max_bytes=10 * nbytes)
    st.put((0,), entry(rng))
    st.put((1,), entry(rng))            # (0,) on disk now
    swp = [f for f in os.listdir(tmp_path) if f.endswith(".swp")]
    assert swp
    with open(os.path.join(tmp_path, swp[0]), "r+b") as fh:
        fh.seek(10)
        fh.write(b"\xff" * 32)          # same size, wrong bytes -> CRC
    assert st.get((0,)) is None         # miss, not a crash
    assert st.stats["corrupt"] == 1


def test_store_disk_truncation_is_miss(tmp_path):
    rng = np.random.default_rng(6)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=nbytes,
                       disk_path=str(tmp_path), disk_max_bytes=10 * nbytes)
    st.put((0,), entry(rng))
    st.put((1,), entry(rng))
    swp = [f for f in os.listdir(tmp_path) if f.endswith(".swp")]
    path = os.path.join(tmp_path, swp[0])
    with open(path, "r+b") as fh:       # torn write: half the file
        fh.truncate(os.path.getsize(path) // 2)
    assert st.get((0,)) is None
    assert st.stats["corrupt"] == 1


def test_store_disk_files_namespaced_per_store(tmp_path):
    """Replicas share one disk_path (the frontend applies a single
    config fleet-wide): two stores must never overwrite or delete each
    other's spill files."""
    rng = np.random.default_rng(60)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    a = TieredKVStore(host_max_bytes=nbytes, disk_path=str(tmp_path),
                      disk_max_bytes=10 * nbytes)
    b = TieredKVStore(host_max_bytes=nbytes, disk_path=str(tmp_path),
                      disk_max_bytes=10 * nbytes)
    ea, eb = entry(rng), entry(rng)
    a.put((0,), ea)
    a.put((1,), entry(rng))             # a's (0,) demotes to disk
    b.put((0,), eb)
    b.put((1,), entry(rng))             # b's (0,) demotes to disk
    got_a, got_b = a.get((0,)), b.get((0,))
    assert got_a is not None and got_b is not None
    np.testing.assert_array_equal(got_a["k"], ea["k"])
    np.testing.assert_array_equal(got_b["k"], eb["k"])
    assert a.stats["corrupt"] == 0 and b.stats["corrupt"] == 0


def test_store_counters_stay_monotonic_through_readmit():
    """The published spill counter must never dip — a transient
    decrement would read as an engine swap to the frontend's
    counter-reset heuristic. readmit re-inserts WITHOUT counting."""
    rng = np.random.default_rng(61)
    st = TieredKVStore(host_max_bytes=1 << 20)
    st.put((0,), entry(rng))
    assert st.stats == {**st.stats, "spilled": 1}
    got = st.get((0,))
    st.readmit((0,), got)
    assert st.stats["spilled"] == 1     # unchanged, not 2-then-1
    assert st.stats["hits"] == 0 and st.stats["misses"] == 1
    assert (0,) in st                   # entry really is back


def test_store_sweeps_dead_owner_files_keeps_live_ones(tmp_path):
    """A shared disk_path must not grow without bound across process
    restarts: construction removes spill files whose owning pid is
    dead, and leaves this process's (and undecidable) files alone."""
    rng = np.random.default_rng(62)
    stale = os.path.join(tmp_path, "kvtier_999999999_0_0.swp")
    with open(stale, "wb") as fh:
        fh.write(b"x" * 64)
    mine = os.path.join(tmp_path, f"kvtier_{os.getpid()}_77_0.swp")
    with open(mine, "wb") as fh:
        fh.write(b"y" * 64)
    other = os.path.join(tmp_path, "unrelated.swp")
    with open(other, "wb") as fh:
        fh.write(b"z" * 64)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=nbytes, disk_path=str(tmp_path),
                       disk_max_bytes=10 * nbytes)
    assert not os.path.exists(stale)        # dead owner: swept
    assert os.path.exists(mine)             # this process: kept
    assert os.path.exists(other)            # not ours to judge: kept
    st.close()


def test_store_close_removes_own_disk_files(tmp_path):
    """A replaced engine's store (supervisor restart) must not orphan
    its spill files until process exit — close() cleans them up."""
    rng = np.random.default_rng(63)
    one = entry(rng)
    nbytes = sum(a.nbytes for a in one.values())
    st = TieredKVStore(host_max_bytes=nbytes, disk_path=str(tmp_path),
                       disk_max_bytes=10 * nbytes)
    st.put((0,), entry(rng))
    st.put((1,), entry(rng))                # (0,) demoted to disk
    assert [f for f in os.listdir(tmp_path) if f.endswith(".swp")]
    st.close()
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".swp")]


def test_store_disk_only_configuration_demotes_directly(tmp_path):
    """An entry too big for the host bound goes STRAIGHT to the disk
    tier when one exists (disk-heavy configs must not be silently
    dead); without a disk tier it drops as before."""
    rng = np.random.default_rng(64)
    e = entry(rng)
    st = TieredKVStore(host_max_bytes=16,       # smaller than any entry
                       disk_path=str(tmp_path), disk_max_bytes=1 << 20)
    assert st.put((0,), e)
    assert st.occupancy()["host_blocks"] == 0
    assert st.occupancy()["disk_blocks"] == 1
    assert st.stats["spilled"] == 1 and st.stats["dropped"] == 0
    got = st.get((0,))
    np.testing.assert_array_equal(got["k"], e["k"])
    st.close()
    no_disk = TieredKVStore(host_max_bytes=16)
    assert not no_disk.put((0,), entry(rng))
    assert no_disk.stats["dropped"] == 1


def test_tier_pressure_baseline_survives_transient_stats_failure():
    """A replica whose tier_stats() read fails during an emitting tick
    must keep its baseline — wholesale replacement would re-emit its
    lifetime totals as a phantom burst when it recovers."""
    from types import SimpleNamespace

    from deepspeed_tpu.serving.frontend import ServingFrontend
    from deepspeed_tpu.telemetry.journal import OpsJournal

    class Eng:
        def __init__(self):
            self.s = {"spilled": 0, "restored": 0, "dropped": 0,
                      "host_bytes": 0}
            self.fail = False

        def tier_stats(self):
            if self.fail:
                raise RuntimeError("transient")
            return dict(self.s)

    e1, e2 = Eng(), Eng()
    fe = SimpleNamespace(
        router=SimpleNamespace(replicas=[
            SimpleNamespace(replica_id=0, engine=e1),
            SimpleNamespace(replica_id=1, engine=e2)]),
        journal=OpsJournal(source="serving"),
        _tier_last={}, _tier_journal_t=-10.0)
    tick = ServingFrontend._maybe_journal_tier_pressure
    e1.s["spilled"] = 10
    tick(fe)
    fe._tier_journal_t = -10.0
    assert fe.journal.count("kv_tier_pressure") == 1
    e1.fail = True                  # transient read failure on e1...
    e2.s["spilled"] = 5             # ...while e2's churn emits
    tick(fe)
    fe._tier_journal_t = -10.0
    assert fe.journal.count("kv_tier_pressure") == 2
    e1.fail = False                 # e1 recovers, counters unchanged
    tick(fe)
    evs = fe.journal.events(kinds=("kv_tier_pressure",))
    assert len(evs) == 2            # no phantom re-emit of e1's 10
    assert evs[0]["detail"]["spilled"] == 10
    assert evs[1]["detail"]["spilled"] == 5


# --------------------------------------------------- spill/restore invariants
@pytest.mark.parametrize("quant,qdtype",
                         [(False, "int8"), (True, "int8"),
                          (True, "fp8_e4m3")],
                         ids=["fp", "int8", "fp8"])
def test_spill_restore_byte_roundtrip(model_and_params, quant, qdtype):
    """An evicted block's slabs (int8/fp8 + scale planes under kv_quant)
    must come back bit-identical when the prefix is matched again — the
    ISSUE 13 dtype axis rides the same test, not a copy."""
    model, params = model_and_params
    rng = np.random.default_rng(7)
    eng = make_engine(model, params, quant=quant, qdtype=qdtype,
                      kv_blocks=16)
    prompt = rand_prompt(rng, 3 * BS + 2)
    eng.put([1], [prompt])
    sm = eng.state_manager
    seq = sm.get_sequence(1)
    indexed = list(seq.kv_blocks[:3])   # 3 full indexed blocks
    before = {b: block_slabs(eng, b) for b in indexed}
    keys = [sm._block_hash[b] for b in indexed]
    eng.flush(1)
    assert sm._evict(3) == 3            # spill all three
    t = eng.tier_stats()
    assert t["spilled"] == 3 and t["host_blocks"] == 3
    matched = eng.match_prefix(2, prompt)
    assert matched == 3 * BS
    assert eng.tier_stats()["restored"] == 3
    seq2 = sm.get_sequence(2)
    for i, b_new in enumerate(seq2.kv_blocks):
        after = block_slabs(eng, b_new)
        for name in after:
            np.testing.assert_array_equal(
                after[name], before[indexed[i]][name]), name
        # restored under the ORIGINAL (parent_hash, tokens) key
        assert sm._block_hash[b_new] == keys[i]
        assert sm._index[keys[i]] == b_new


def test_bf16_roundtrip_parity():
    """bf16 pools spill/restore exactly (np round-trips ml_dtypes)."""
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=64,
                            intermediate_size=128, num_layers=2,
                            num_heads=2, max_seq_len=128, norm="rmsnorm",
                            activation="silu", position="rope",
                            dtype=jnp.bfloat16)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(8)
    reqs = shared_prefix_reqs(rng)
    g_off = greedy_generate(make_engine(model, params, tier=False), reqs,
                            uid_base=100, max_new_tokens=4)
    eng = make_engine(model, params, tier=True)
    g_on = greedy_generate(eng, reqs, uid_base=100, max_new_tokens=4)
    assert eng.tier_stats()["restored"] > 0
    assert g_on == g_off


def test_referenced_block_never_spilled(model_and_params):
    """A block still shared by a live sequence is not evictable, so it
    can never reach the tier — eviction (and spill) touch only blocks
    whose sole reference is the cache's own."""
    model, params = model_and_params
    rng = np.random.default_rng(9)
    eng = make_engine(model, params, kv_blocks=16)
    prompt = rand_prompt(rng, 2 * BS + 1)
    eng.put([1], [prompt])              # seq 1 holds its blocks
    sm = eng.state_manager
    held = list(sm.get_sequence(1).kv_blocks[:2])
    assert sm._evict(10) == 0           # everything referenced: no-op
    assert eng.tier_stats()["spilled"] == 0
    for b in held:
        assert b in sm._block_hash      # still indexed, still resident


def test_partial_block_never_spilled(model_and_params):
    """The last, partially-filled block is never indexed, so eviction
    (and therefore the tier) can never see it."""
    model, params = model_and_params
    rng = np.random.default_rng(10)
    eng = make_engine(model, params, kv_blocks=16)
    prompt = rand_prompt(rng, BS + 3)   # 1 full + 1 partial block
    eng.put([1], [prompt])
    eng.flush(1)
    sm = eng.state_manager
    assert sm._evict(10) == 1           # only the full block was indexed
    assert eng.tier_stats()["spilled"] == 1


def test_restore_shares_lru_with_device_hits(model_and_params):
    """After a restore the block behaves exactly like a device-indexed
    block: a second match of the same prefix hits the index without
    touching the tier."""
    model, params = model_and_params
    rng = np.random.default_rng(11)
    eng = make_engine(model, params, kv_blocks=16)
    prompt = rand_prompt(rng, 2 * BS + 2)
    eng.put([1], [prompt])
    eng.flush(1)
    sm = eng.state_manager
    sm._evict(2)
    assert eng.match_prefix(2, prompt) == 2 * BS
    hits0 = eng.tier_stats()["hits"]
    assert eng.match_prefix(3, prompt) == 2 * BS    # pure device hits
    assert eng.tier_stats()["hits"] == hits0
    eng.flush(2)
    eng.flush(3)


def test_restore_under_full_pool_evicts_or_degrades(model_and_params):
    """A tier hit with zero free blocks evicts a colder cache resident
    to make room; when nothing is evictable the match degrades to a
    re-prefill (miss) instead of raising."""
    model, params = model_and_params
    rng = np.random.default_rng(12)
    eng = make_engine(model, params, kv_blocks=6, max_seqs=4)
    sm = eng.state_manager
    p_a = rand_prompt(rng, 2 * BS + 2)  # 2 full blocks + a match tail
    eng.put([1], [p_a])
    eng.flush(1)
    sm._evict(2)                        # A spilled to the tier
    # fill the pool with a live (referenced) sequence: nothing evictable
    p_b = rand_prompt(rng, 5 * BS + 3)
    eng.put([2], [p_b[:32]])
    eng.put([2], [p_b[32:]])
    assert sm.allocator.free_blocks == 0
    assert sm.evictable_blocks == 0
    # restore impossible: the walk degrades to a miss, no exception
    assert eng.match_prefix(3, p_a) == 0
    assert eng.tier_stats()["restored"] == 0
    # counters describe the degrade honestly: the failed restore is a
    # MISS (not a hit) and the readmit is not a new spill
    assert eng.tier_stats()["hits"] == 0
    assert eng.tier_stats()["misses"] >= 1
    assert eng.tier_stats()["spilled"] == 2
    # the entry survived for a calmer moment
    assert sm._tier is not None and len(sm._tier) >= 1
    eng.flush(2)
    eng.flush(3)
    # with the pool free again the same match restores
    assert eng.match_prefix(4, p_a) == 2 * BS
    assert eng.tier_stats()["restored"] == 2


def test_disk_corrupt_entry_reprefills_end_to_end(model_and_params,
                                                  tmp_path):
    """Corrupting the on-disk spill file must degrade that prefix to a
    re-prefill — generations still complete, streams still match."""
    model, params = model_and_params
    rng = np.random.default_rng(13)
    # host tier sized for ~1 block so spills demote to disk immediately
    eng = make_engine(model, params, kv_blocks=14, host_bytes=9000,
                      disk_path=str(tmp_path), disk_bytes=1 << 20)
    reqs = shared_prefix_reqs(rng)
    g_ref = greedy_generate(make_engine(model, params, tier=False), reqs,
                            uid_base=300, max_new_tokens=4)
    sched = ContinuousBatchingScheduler(eng)
    for i, p in enumerate(reqs[:6]):
        sched.submit(300 + i, p, max_new_tokens=4)
        sched.run_to_completion()
    assert eng.tier_stats()["disk_blocks"] > 0
    for f in os.listdir(tmp_path):      # corrupt EVERY spill file
        if f.endswith(".swp"):
            with open(os.path.join(tmp_path, f), "r+b") as fh:
                fh.seek(0)
                fh.write(b"\xde\xad\xbe\xef" * 4)
    gens = []
    for i, p in enumerate(reqs[6:]):
        sched.submit(400 + i, p, max_new_tokens=4)
        sched.run_to_completion()
        gens.append(sched.finished[400 + i].generated)
    assert gens == g_ref[6:]            # re-prefilled, never crashed
    assert eng.tier_stats()["corrupt"] > 0


# ----------------------------------------------------------- disabled parity
def test_disabled_path_byte_identical(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(14)
    reqs = shared_prefix_reqs(rng)
    g_plain = greedy_generate(make_engine(model, params, tier=False),
                              reqs, uid_base=500, max_new_tokens=4)
    # config present but disabled: identical engine behavior
    vcfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=128, max_ragged_sequence_count=4,
        max_chunk_tokens=32, kv_blocks=14, kv_block_size=BS,
        max_tracked_sequences=64, enable_prefix_cache=True,
        kv_tier_enabled=False)
    g_dis = greedy_generate(InferenceEngineV2(model, params=params,
                                              config=vcfg),
                            reqs, uid_base=500, max_new_tokens=4)
    assert g_dis == g_plain


def test_configure_host_bytes_preserves_disk_tier(model_and_params,
                                                  tmp_path):
    """Re-tuning only the host bound must not silently destroy a
    configured disk tier (None arguments preserve config values)."""
    model, params = model_and_params
    eng = make_engine(model, params, tier=True,
                      disk_path=str(tmp_path), disk_bytes=1 << 20)
    eng.configure_kv_tier(True, host_bytes=128 << 20)
    assert eng.config.kv_tier_host_bytes == 128 << 20
    assert eng.config.kv_tier_disk_path == str(tmp_path)
    assert eng.config.kv_tier_disk_bytes == 1 << 20
    assert eng.state_manager._tier._swapper is not None
    # explicit drop: disk_bytes=0
    eng.configure_kv_tier(True, disk_bytes=0)
    assert eng.state_manager._tier._swapper is None


def test_tier_requires_prefix_cache(model_and_params):
    model, params = model_and_params
    eng = make_engine(model, params, tier=False, prefix=False)
    with pytest.raises(ValueError, match="prefix cache"):
        eng.configure_kv_tier(True)
    # the rejected configure must not leave config claiming a tier the
    # manager never built (an engine rebuilt from it would raise)
    assert not eng.config.kv_tier_enabled
    assert not eng.state_manager.kv_tier_enabled


def test_disabling_prefix_cache_tears_down_tier(model_and_params):
    model, params = model_and_params
    eng = make_engine(model, params, tier=True)
    assert eng.state_manager.kv_tier_enabled
    eng.configure_prefix_cache(False)
    assert not eng.state_manager.kv_tier_enabled
    assert not eng.config.kv_tier_enabled


def test_occupancy_carries_tier_fields(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(15)
    for tier in (False, True):
        occ = make_engine(model, params, tier=tier).occupancy()
        for k in ("kv_blocks_host_tier", "kv_bytes_host_tier",
                  "kv_blocks_disk_tier", "kv_bytes_disk_tier"):
            assert isinstance(occ[k], int) and occ[k] == 0
    eng = make_engine(model, params, tier=True)
    sched = ContinuousBatchingScheduler(eng)
    for i, p in enumerate(shared_prefix_reqs(rng)):
        sched.submit(600 + i, p, max_new_tokens=3)
        sched.run_to_completion()
    occ = eng.occupancy()
    assert occ["kv_blocks_host_tier"] > 0
    assert occ["kv_bytes_host_tier"] > 0


# --------------------------------------------------------------- serving e2e
def serving_config(**kv_tier):
    from deepspeed_tpu.serving import ServingConfig

    return ServingConfig(max_queue_depth=64,
                         prefix_cache={"enabled": True},
                         kv_tier=(kv_tier or {"enabled": True}),
                         # reservation admission makes small-pool
                         # concurrency safe (docs/SERVING.md "Admission
                         # and preemption"), so these tests no longer
                         # have to size max_seqs below the pool
                         admission={"reservation": True})


def test_frontend_applies_tier_and_publishes_metrics(model_and_params):
    from deepspeed_tpu.serving import ServingFrontend

    model, params = model_and_params
    rng = np.random.default_rng(16)
    reqs = shared_prefix_reqs(rng)
    eng = make_engine(model, params, tier=False, prefix=False)
    fe = ServingFrontend([eng], serving_config())
    try:
        assert eng.state_manager.kv_tier_enabled     # config applied it
        handles = [fe.submit(p, max_new_tokens=4) for p in reqs]
        assert fe.wait_all(handles, timeout=120)
        snap = fe.metrics_snapshot()
        assert snap["kv_tier_blocks_spilled"] > 0
        assert snap["kv_tier_blocks_restored"] > 0
        assert snap["kv_blocks_host_tier"] > 0
        assert snap["kv_tier_bytes_host"] > 0
        assert snap["kv_tier_restore_s"]["count"] > 0
        # pressure events land in the ops journal (bypass the ~1s gate)
        fe._tier_journal_t = -10.0
        fe._maybe_journal_tier_pressure()
        assert fe.journal.count("kv_tier_pressure") >= 1
        ev = fe.journal.events(kinds=("kv_tier_pressure",))[-1]
        assert ev["detail"]["spilled"] > 0
        rep = fe.health_report(window_s=60.0)
        assert rep["occupancy"]["kv_blocks_host_tier"] > 0
        assert "kv_tier_restore_s" in rep["window"]
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_restore_races_cancel_and_deadline(model_and_params):
    """Cancels and deadline expiries racing tier restores must settle
    terminally with the KV pool fully reclaimed — a restored block whose
    request dies goes back through the normal flush/eviction path."""
    from deepspeed_tpu.serving import RequestState, ServingFrontend

    model, params = model_and_params
    rng = np.random.default_rng(17)
    reqs = shared_prefix_reqs(rng, n_req=10)
    eng = make_engine(model, params, tier=False, prefix=False,
                      kv_blocks=24)
    fe = ServingFrontend([eng], serving_config())
    try:
        warm = [fe.submit(p, max_new_tokens=3) for p in reqs]
        assert fe.wait_all(warm, timeout=120)       # tier now warm
        handles = []
        for i, p in enumerate(reqs):
            if i % 3 == 2:
                h = fe.submit(p, max_new_tokens=40, deadline_ms=1.0)
            else:
                h = fe.submit(p, max_new_tokens=40)
            handles.append(h)
            if i % 3 == 0:
                fe.cancel(h)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and any(
                h.state in (RequestState.QUEUED, RequestState.RUNNING)
                for h in handles):
            time.sleep(0.02)
        assert all(h.state not in (RequestState.QUEUED,
                                   RequestState.RUNNING)
                   for h in handles), [h.state for h in handles]
    finally:
        fe.shutdown(drain=False, timeout=5)
    # all sequence KV returned; only the prefix cache's refs remain
    occ = eng.occupancy()
    assert occ["available_blocks"] == occ["total_blocks"]


def test_restore_survives_replica_death(model_and_params):
    """A replica crash mid-burst with the tier active: requests fail
    over and resume on the replacement with streams matching an
    unfaulted tier run (the PR 5 failover path composes with restores)."""
    from deepspeed_tpu.serving import (RequestState, ServingConfig,
                                       ServingFrontend)

    model, params = model_and_params
    rng = np.random.default_rng(18)
    reqs = shared_prefix_reqs(rng, n_req=8)

    def factory(i):
        return make_engine(model, params, tier=False, prefix=False,
                           max_seqs=2)

    def run(faulted):
        scfg = ServingConfig(
            max_queue_depth=64,
            prefix_cache={"enabled": True},
            kv_tier={"enabled": True},
            fault_tolerance={"enabled": True, "max_retries": 3,
                             "restart_backoff_s": 0.05,
                             "supervisor_poll_s": 0.02},
            faults=({"enabled": True, "schedule": [
                {"kind": "crash", "replica": 0, "at_step": 4}]}
                if faulted else {"enabled": False}))
        fe = ServingFrontend([factory(0)], scfg, engine_factory=factory)
        try:
            handles = [fe.submit(p, max_new_tokens=5) for p in reqs]
            assert fe.wait_all(handles, timeout=180)
            states = [h.state for h in handles]
            gens = [[ev.token for ev in h.drain()] for h in handles]
        finally:
            fe.shutdown(drain=False, timeout=5)
        assert all(s == RequestState.FINISHED for s in states), states
        return gens

    assert run(True) == run(False)


# --------------------------------------------------------------- config
def test_kv_tier_config_apply():
    kt = KVTierConfig(enabled=True, host_max_bytes=123, disk_path="/x",
                      disk_max_bytes=456)
    vcfg = RaggedInferenceEngineConfig()
    kt.apply(vcfg)
    assert vcfg.kv_tier_enabled
    assert vcfg.kv_tier_host_bytes == 123
    assert vcfg.kv_tier_disk_path == "/x"
    assert vcfg.kv_tier_disk_bytes == 456


def test_ds_config_mounts_kv_tier():
    from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig

    c = DeepSpeedTpuConfig(**{"train_micro_batch_size_per_gpu": 1,
                              "kv_tier": {"enabled": True,
                                          "host_max_bytes": 99},
                              "serving": {"kv_tier": {"enabled": True}}})
    assert c.kv_tier.enabled and c.kv_tier.host_max_bytes == 99
    assert c.serving.kv_tier.enabled
