"""Disaggregated prefill/decode serving (ISSUE 8).

Covers the KV handoff machinery at every layer: state-manager block
export/import (byte-parity round trips, fp and quantized; representation
mismatches; capacity failure atomicity; prefix-index coherence), the
scheduler's prefill-only and decode-reserve roles, the role-split
frontend end to end (greedy byte-parity vs the mixed stack, handoff
racing cancel/deadline/replica death, recompute fallback), the
class-aware admission queue (per-class depth/shed counters, brownout
ordering: batch before interactive), and the disabled-path guarantee —
``disaggregation.enabled=false`` is byte-for-byte the historical
scheduler/router (docs/SERVING.md "Disaggregated serving").
"""

import time

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig
from deepspeed_tpu.serving import (AdmissionQueue, FinishReason, Priority,
                                   RequestState, ServingConfig,
                                   ServingFrontend, ServingRequest,
                                   serving_metrics)

VOCAB = 128

_model = None
_params = None


def tiny_engine(i=0, **cfg_over):
    """Fresh engine over a module-shared model/params."""
    global _model, _params
    if _model is None:
        _model = CausalLM(TransformerConfig(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=2, max_seq_len=128, norm="rmsnorm",
            activation="silu", position="rope"))
    base = dict(max_ragged_batch_size=128, max_ragged_sequence_count=4,
                max_chunk_tokens=32, kv_blocks=64, kv_block_size=8,
                max_tracked_sequences=16)
    base.update(cfg_over)
    eng = InferenceEngineV2(_model, params=_params,
                            config=RaggedInferenceEngineConfig(**base))
    _params = eng.params
    return eng


def prompts(n, seed, lo=8, hi=24):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, size=int(l)).tolist()
            for l in rng.integers(lo, hi, size=n)]


def prefill_to_payload(eng, uid, prompt, max_new=8):
    """Run a prefill-only scheduler to completion and export the KV."""
    sched = ContinuousBatchingScheduler(eng, prefill_only=True)
    sched.submit(uid, prompt, max_new_tokens=max_new)
    sched.run_to_completion()
    sreq = sched.finished[uid]
    assert sreq.finish_reason == "prefilled"
    payload = eng.export_sequence(uid)
    payload["last_logits"] = sreq.last_logits
    eng.flush(uid)
    return payload


def greedy_reference(ps, max_new):
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=64))
    try:
        hs = [fe.submit(p, max_new_tokens=max_new) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        return [[ev.token for ev in h.drain()] for h in hs]
    finally:
        fe.shutdown(drain=False, timeout=5)


def disagg_config(roles, **over):
    dis = {"enabled": True, "roles": roles, "decode_reserve_tokens": 8}
    dis.update(over)
    return ServingConfig(max_queue_depth=64, disaggregation=dis)


# ------------------------------------------------- export/import round trip
@pytest.mark.parametrize("quant,qdtype",
                         [(False, "int8"), (True, "int8"),
                          (True, "fp8_e4m3")],
                         ids=["fp", "int8", "fp8"])
def test_export_import_roundtrip_byte_parity(quant, qdtype):
    """Imported KV must be byte-identical to the exported blocks (slab
    compare) AND resume decoding byte-losslessly (greedy compare vs an
    uninterrupted run) — the ISSUE 13 dtype axis (fp8_e4m3 pools) rides
    the same test, not a copy."""
    prompt = prompts(1, seed=1, lo=20, hi=21)[0]
    ref_eng = tiny_engine(kv_quant_enabled=quant, kv_quant_dtype=qdtype)
    sref = ContinuousBatchingScheduler(ref_eng)
    sref.submit(1, prompt, max_new_tokens=8)
    sref.run_to_completion()
    ref = sref.finished[1].generated

    src = tiny_engine(kv_quant_enabled=quant, kv_quant_dtype=qdtype)
    payload = prefill_to_payload(src, 2, prompt)
    assert payload["kv_quant"] is quant
    assert payload["seen_tokens"] == len(prompt)
    if quant:
        assert payload["kv_quant_dtype"] == qdtype
        assert "k_scale" in payload["slabs"] and "v_scale" in payload["slabs"]

    dst = tiny_engine(kv_quant_enabled=quant, kv_quant_dtype=qdtype)
    dst.import_sequence(3, payload, tokens=prompt)
    # slab-level byte parity: re-export from the destination
    back = dst.export_sequence(3)
    assert back["seen_tokens"] == payload["seen_tokens"]
    for key in payload["slabs"]:
        assert np.array_equal(payload["slabs"][key], back["slabs"][key]), key
    # stream-level byte parity: resume and compare to the plain run
    sched = ContinuousBatchingScheduler(dst)
    sched.submit_prefilled(3, prompt, payload["last_logits"],
                           max_new_tokens=8)
    sched.run_to_completion()
    assert sched.finished[3].generated == ref


def test_export_unknown_sequence_returns_none():
    eng = tiny_engine()
    assert eng.export_sequence(999) is None


def test_import_rejects_representation_mismatches():
    prompt = prompts(1, seed=2, lo=16, hi=17)[0]
    src = tiny_engine()
    payload = prefill_to_payload(src, 1, prompt)
    # kv_quant mismatch
    with pytest.raises(ValueError, match="representation"):
        tiny_engine(kv_quant_enabled=True).import_sequence(
            2, payload, tokens=prompt)
    # dtype mismatch within kv_quant (int8 payload into fp8 pools —
    # a heterogeneous fleet must recompute instead)
    qpayload = prefill_to_payload(tiny_engine(kv_quant_enabled=True),
                                  7, prompt)
    with pytest.raises(ValueError, match="kv_quant_dtype"):
        tiny_engine(kv_quant_enabled=True,
                    kv_quant_dtype="fp8_e4m3").import_sequence(
            8, qpayload, tokens=prompt)
    # block-size mismatch
    with pytest.raises(ValueError, match="block_size"):
        tiny_engine(kv_block_size=16).import_sequence(
            3, payload, tokens=prompt)
    # token list must match the KV content length
    with pytest.raises(ValueError, match="tokens"):
        tiny_engine().import_sequence(4, payload, tokens=prompt[:3])


def test_import_capacity_failure_leaves_engine_untouched():
    prompt = prompts(1, seed=3, lo=20, hi=21)[0]
    payload = prefill_to_payload(tiny_engine(), 1, prompt)
    dst = tiny_engine(kv_blocks=2)
    free0 = dst.free_blocks
    with pytest.raises(RuntimeError, match="cannot import"):
        dst.import_sequence(2, payload, tokens=prompt)
    assert dst.free_blocks == free0
    assert dst.state_manager.get_sequence(2) is None


def test_import_refuses_sequence_with_state():
    prompt = prompts(1, seed=4, lo=16, hi=17)[0]
    payload = prefill_to_payload(tiny_engine(), 1, prompt)
    dst = tiny_engine()
    dst.put([7], [prompt[:8]])
    with pytest.raises(ValueError, match="already has KV state"):
        dst.import_sequence(7, payload, tokens=prompt)


def test_prefix_index_coherent_after_import():
    """Imported full blocks must register in the destination's prefix
    index (the hash chain replays over the real tokens), so later
    prompts sharing the prefix hit the cache exactly as if the prefill
    had run locally."""
    prompt = prompts(1, seed=5, lo=20, hi=21)[0]
    payload = prefill_to_payload(tiny_engine(), 1, prompt)
    dst = tiny_engine(enable_prefix_cache=True)
    dst.import_sequence(2, payload, tokens=prompt)
    matched = dst.match_prefix(3, prompt + [1, 2, 3])
    # every full 8-token block of the 20-token prompt is shared
    assert matched == (len(prompt) // 8) * 8
    assert dst.prefix_stats()["hits"] >= 2


# ---------------------------------------------------------- scheduler roles
def test_prefill_only_finishes_prefilled_and_keeps_kv():
    eng = tiny_engine()
    prompt = prompts(1, seed=6, lo=20, hi=21)[0]
    sched = ContinuousBatchingScheduler(eng, prefill_only=True)
    sched.submit(1, prompt, max_new_tokens=8)
    sched.run_to_completion()
    req = sched.finished[1]
    assert req.finish_reason == "prefilled"
    assert req.generated == []               # never decodes a token
    assert req.last_logits is not None       # the handoff's first sample
    # KV deliberately resident: the serving layer exports then flushes
    assert eng.query(1) == (len(prompt), -(-len(prompt) // 8))


def test_decode_reserve_caps_prompt_chunks():
    """A decode-role scheduler holds the unused reservation back from
    prompt chunks — and an over-sized reservation degrades prefill to
    one token per step instead of wedging it."""
    eng = tiny_engine()
    prompt = prompts(1, seed=7, lo=30, hi=31)[0]
    sched = ContinuousBatchingScheduler(eng, decode_reserve_tokens=120)
    # budget 128, chunk 32: reserve 120 leaves 8 prompt tokens per step
    sched.submit(1, prompt, max_new_tokens=2)
    sched.step()
    assert sched.running[1].prompt_fed == 8
    # pathological reserve >= budget still makes progress (1 token/step)
    eng2 = tiny_engine()
    sched2 = ContinuousBatchingScheduler(eng2, decode_reserve_tokens=500)
    sched2.submit(1, prompt, max_new_tokens=2)
    sched2.step()
    assert sched2.running[1].prompt_fed == 1


def test_decode_reserve_zero_is_historical_packing():
    eng = tiny_engine()
    prompt = prompts(1, seed=8, lo=40, hi=41)[0]
    sched = ContinuousBatchingScheduler(eng, decode_reserve_tokens=0)
    sched.submit(1, prompt, max_new_tokens=2)
    sched.step()
    assert sched.running[1].prompt_fed == 32        # full chunk


# ------------------------------------------------------- frontend end-to-end
def test_disagg_frontend_byte_parity_and_handoffs():
    ps = prompts(5, seed=9)
    ref = greedy_reference(ps, max_new=6)
    fe = ServingFrontend([tiny_engine(), tiny_engine()],
                         disagg_config(["prefill", "decode"]))
    try:
        assert fe.router.replicas[0].role == "prefill"
        assert fe.router.replicas[1].role == "decode"
        hs = [fe.submit(p, max_new_tokens=6) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in hs)
        got = [[ev.token for ev in h.drain()] for h in hs]
        assert got == ref, "disaggregated serving broke greedy byte-parity"
        snap = fe.metrics_snapshot()
        assert snap["handoffs_started"] == len(ps)
        assert snap["handoffs_completed"] == len(ps)
        assert snap["handoff_fallbacks"] == 0
        assert snap["handoff_s"]["count"] == len(ps)
        # staging buffer fully drained
        assert len(fe._stager) == 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_disagg_composes_with_prefix_cache_and_kv_quant():
    ps = [prompts(1, seed=10, lo=20, hi=21)[0]] * 3   # shared prefix
    ref = greedy_reference(ps, max_new=5)
    scfg = disagg_config(["prefill", "decode"])
    scfg.prefix_cache.enabled = True
    scfg.kv_quant.enabled = True
    fe = ServingFrontend([tiny_engine(), tiny_engine()], scfg)
    try:
        hs = [fe.submit(p, max_new_tokens=5) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        got = [[ev.token for ev in h.drain()] for h in hs]
        snap = fe.metrics_snapshot()
        assert snap["handoffs_completed"] == len(ps)
        # int8 KV is bounded-divergent in general, but these tiny
        # prompts stay exact — what matters here is completion without
        # fallbacks and the quantized slabs riding the handoff intact
        assert all(len(g) == 5 for g in got)
        assert snap["handoff_fallbacks"] == 0
    finally:
        fe.shutdown(drain=False, timeout=5)
    assert [len(g) for g in got] == [len(r) for r in ref]


def test_handoff_full_staging_buffer_falls_back_to_recompute():
    ps = prompts(4, seed=11)
    ref = greedy_reference(ps, max_new=5)
    fe = ServingFrontend(
        [tiny_engine(), tiny_engine()],
        disagg_config(["prefill", "decode"],
                      handoff={"enabled": True, "max_staged": 1}))
    try:
        # saturate the single staging slot so some handoffs degrade
        hs = [fe.submit(p, max_new_tokens=5) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in hs)
        got = [[ev.token for ev in h.drain()] for h in hs]
        assert got == ref, "recompute fallback broke greedy byte-parity"
        snap = fe.metrics_snapshot()
        assert snap["handoffs_completed"] + snap["handoff_fallbacks"] \
            >= len(ps)
        assert len(fe._stager) == 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_disagg_disabled_is_byte_identical_mixed_stack():
    """disaggregation.enabled=false (block present): every replica is
    mixed, the router runs the unweighted historical cost, no handoff
    hooks exist — byte-for-byte the PR 7 behavior."""
    ps = prompts(4, seed=12)
    ref = greedy_reference(ps, max_new=6)
    scfg = ServingConfig(max_queue_depth=64, disaggregation={
        "enabled": False, "roles": ["prefill", "decode"],
        "decode_reserve_tokens": 100})
    fe = ServingFrontend([tiny_engine(), tiny_engine()], scfg)
    try:
        assert fe._disagg is None and fe._stager is None
        assert fe.router.disaggregation is None
        for r in fe.router.replicas:
            assert r.role == "mixed"
            assert r._on_handoff is None
            assert r.scheduler.prefill_only is False
            assert r.scheduler.decode_reserve_tokens == 0
        hs = [fe.submit(p, max_new_tokens=6) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        got = [[ev.token for ev in h.drain()] for h in hs]
        assert got == ref
        assert fe.metrics_snapshot()["handoffs_started"] == 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_handoff_races_cancel_and_deadline_settle_terminally():
    fe = ServingFrontend([tiny_engine(), tiny_engine()],
                         disagg_config(["prefill", "decode"]))
    try:
        # cancel racing the handoff pipeline: terminal CANCELLED, no hang
        h = fe.submit(prompts(1, seed=13, lo=20, hi=24)[0],
                      max_new_tokens=50)
        h.cancel()
        assert h._req.wait(60), "cancelled request never settled"
        assert h.state in (RequestState.CANCELLED, RequestState.FINISHED)
        # deadline too short to survive prefill+handoff: terminal EXPIRED
        h2 = fe.submit(prompts(1, seed=14, lo=20, hi=24)[0],
                       max_new_tokens=50, deadline_ms=1.0)
        assert h2._req.wait(60), "expired request never settled"
        assert h2.state == RequestState.EXPIRED
        # staging slots all freed — a dead staged request can't pin them
        deadline = time.monotonic() + 30
        while len(fe._stager) and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(fe._stager) == 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_prefill_replica_death_fails_over_cleanly():
    """A prefill-role replica dying mid-prefill: its requests fail over
    (PR 5 path), resume elsewhere, and the stream stays byte-identical;
    the supervisor restarts the slot with its prefill role intact."""
    ps = prompts(3, seed=15)
    ref = greedy_reference(ps, max_new=6)
    scfg = disagg_config(["prefill", "decode"])
    scfg.fault_tolerance.enabled = True
    scfg.fault_tolerance.max_retries = 3
    scfg.fault_tolerance.restart_backoff_s = 0.05
    scfg.fault_tolerance.supervisor_poll_s = 0.02
    scfg.faults.enabled = True
    # step 0: the whole burst prefills in ONE packed step on this tiny
    # model, so the crash must hit the first busy step to catch
    # in-flight prefill work
    scfg.faults.schedule = [{"kind": "crash", "replica": 0, "at_step": 0}]
    fe = ServingFrontend([tiny_engine(), tiny_engine()], scfg,
                         engine_factory=tiny_engine)
    try:
        hs = [fe.submit(p, max_new_tokens=6) for p in ps]
        assert fe.wait_all(hs, timeout=300)
        assert all(h.state == RequestState.FINISHED for h in hs)
        got = [[ev.token for ev in h.drain()] for h in hs]
        assert got == ref, "prefill-replica death broke byte-parity"
        # the restarted slot keeps its prefill role
        deadline = time.monotonic() + 60
        while not fe.supervisor.restart_log and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fe.supervisor.restart_log
        assert fe.router.replicas[0].role == "prefill"
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_role_validation_rejects_broken_fleets():
    engines2 = lambda: [tiny_engine(), tiny_engine()]  # noqa: E731
    with pytest.raises(ValueError, match="unknown roles"):
        ServingFrontend(engines2(), disagg_config(["prefill", "verifier"]))
    with pytest.raises(ValueError, match="one role per replica"):
        ServingFrontend(engines2(), disagg_config(["prefill"]))
    with pytest.raises(ValueError, match="decode-capable"):
        ServingFrontend(engines2(), disagg_config(["prefill", "prefill"]))
    with pytest.raises(ValueError, match="handoff.enabled"):
        ServingFrontend(engines2(),
                        disagg_config(["prefill", "decode"],
                                      handoff={"enabled": False}))


def test_weighted_router_cost_splits_prefill_and_decode():
    """The satellite fix: a big pending prefill must not look as heavy
    as the same number of owed decode tokens."""
    from deepspeed_tpu.serving import DisaggregationConfig

    class FakeReplica:
        def __init__(self, rid, pre, dec):
            self.replica_id = rid
            self.outstanding_tokens = pre + dec
            self.outstanding_prefill_tokens = pre
            self.outstanding_decode_tokens = dec

    from deepspeed_tpu.serving.router import ReplicaRouter

    r_prefill = FakeReplica(0, 2000, 0)     # one long pending prompt
    r_decode = FakeReplica(1, 0, 600)       # many owed decode steps
    dis = DisaggregationConfig(enabled=True, prefill_token_cost=1.0,
                               decode_token_cost=8.0)
    cost = ReplicaRouter._cost
    router = type("R", (), {"disaggregation": dis})()
    assert cost(router, r_prefill) < cost(router, r_decode), \
        "2000 prefill tokens must cost less than 600 decode tokens"
    # historical signal would have herded work onto the decode replica
    router_off = type("R", (), {"disaggregation": None})()
    assert cost(router_off, r_prefill) > cost(router_off, r_decode)


# ---------------------------------------------------- class-aware admission
def Req(prompt_len, max_new, priority, deadline_s, cls="interactive",
        shed_rank=0):
    return ServingRequest([1] * prompt_len, max_new, priority, deadline_s,
                          None, request_class=cls, shed_rank=shed_rank)


def test_queue_per_class_depth_and_shed_counters():
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=2, metrics=reg)
    q.offer(Req(4, 4, Priority.NORMAL, None, cls="interactive"))
    q.offer(Req(4, 4, Priority.LOW, None, cls="batch", shed_rank=1))
    snap = reg.snapshot()
    assert snap["queue_depth_class_interactive"] == 1
    assert snap["queue_depth_class_batch"] == 1
    from deepspeed_tpu.serving import Rejected

    with pytest.raises(Rejected):
        q.offer(Req(4, 4, Priority.LOW, None, cls="batch", shed_rank=1))
    snap = reg.snapshot()
    assert snap["requests_shed_class_batch"] == 1
    assert snap["requests_shed_class_interactive"] == 0
    q.pop(timeout=0)
    assert reg.snapshot()["queue_depth_class_interactive"] == 0


def test_brownout_sheds_batch_before_interactive():
    """Class shed rank dominates priority: a HIGH-priority batch request
    is shed before a LOW-priority interactive one."""
    reg = serving_metrics()
    q = AdmissionQueue(max_depth=4, metrics=reg, brownout_threshold=0.6)
    batch_high = Req(4, 4, Priority.HIGH, 10.0, cls="batch", shed_rank=1)
    inter_low = Req(4, 4, Priority.LOW, None, cls="interactive")
    q.offer(batch_high)
    q.offer(inter_low)
    q.offer(Req(4, 4, Priority.NORMAL, None, cls="interactive"))
    q.set_healthy_fraction(0.5)          # effective depth 2: shed one
    assert batch_high.state == RequestState.REJECTED
    assert batch_high.finish_reason == FinishReason.BROWNOUT
    assert inter_low.state == RequestState.QUEUED
    snap = reg.snapshot()
    assert snap["requests_shed_class_batch"] == 1
    assert snap["requests_shed_class_interactive"] == 0


def test_brownout_equal_rank_falls_back_to_urgency():
    """Within one class the historical order holds: lowest priority /
    longest deadline sheds first."""
    q = AdmissionQueue(max_depth=4, brownout_threshold=0.6)
    high = Req(4, 4, Priority.HIGH, 10.0)
    low = Req(4, 4, Priority.LOW, None)
    q.offer(high)
    q.offer(low)
    q.offer(Req(4, 4, Priority.NORMAL, 30.0))
    q.set_healthy_fraction(0.5)
    assert low.state == RequestState.REJECTED
    assert high.state == RequestState.QUEUED


def test_brownout_never_evicts_staged_handoff_requests():
    q = AdmissionQueue(max_depth=4, brownout_threshold=0.6)
    staged = Req(4, 4, Priority.LOW, None, cls="batch", shed_rank=1)
    staged.staged_kv = {"sentinel": True}
    fresh = Req(4, 4, Priority.LOW, None, cls="batch", shed_rank=1)
    q.offer(staged)
    q.offer(fresh)
    q.offer(Req(4, 4, Priority.HIGH, 10.0))
    q.set_healthy_fraction(0.5)
    assert fresh.state == RequestState.REJECTED
    assert staged.state == RequestState.QUEUED


def test_pop_accept_skips_undispatchable_head():
    """The head-of-line fix: a staged decode-phase request at the queue
    head must not block a pop for prefill-capable capacity — the
    predicate skips it (leaving it queued, urgency order intact)."""
    q = AdmissionQueue(max_depth=8)
    staged = Req(4, 4, Priority.HIGH, 10.0)
    staged.staged_kv = {"sentinel": True}
    fresh = Req(4, 4, Priority.LOW, None)
    q.offer(staged)
    q.offer(fresh)
    # only prefill capacity free: the staged head is skipped
    got = q.pop(timeout=0, accept=lambda r: r.staged_kv is None)
    assert got is fresh
    assert len(q) == 1
    # nothing dispatchable → None, entry stays queued
    assert q.pop(timeout=0, accept=lambda r: False) is None
    assert len(q) == 1
    # accept=None = historical pop
    assert q.pop(timeout=0) is staged


def test_unknown_class_rejected_before_submitted_count():
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=8))
    try:
        with pytest.raises(ValueError, match="unknown request class"):
            fe.submit(prompts(1, seed=23)[0], request_class="typo")
        snap = fe.metrics_snapshot()
        assert snap["requests_submitted"] == 0
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_request_class_resolves_policy_defaults():
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=16))
    try:
        h_int = fe.submit(prompts(1, seed=16)[0], max_new_tokens=2)
        assert h_int._req.request_class == "interactive"
        assert h_int._req.priority == 1          # default_priority
        h_b = fe.submit(prompts(1, seed=17)[0], max_new_tokens=2,
                        request_class="batch")
        assert h_b._req.priority == int(Priority.LOW)
        assert h_b._req.shed_rank == 1
        # explicit priority beats the class policy
        h_b2 = fe.submit(prompts(1, seed=18)[0], max_new_tokens=2,
                         request_class="batch", priority=Priority.HIGH)
        assert h_b2._req.priority == int(Priority.HIGH)
        with pytest.raises(ValueError, match="unknown request class"):
            fe.submit(prompts(1, seed=19)[0], request_class="vip")
        fe.wait_all([h_int, h_b, h_b2], timeout=300)
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_per_class_latency_histograms_populate():
    fe = ServingFrontend([tiny_engine()], ServingConfig(max_queue_depth=16))
    try:
        hs = [fe.submit(prompts(1, seed=20)[0], max_new_tokens=4),
              fe.submit(prompts(1, seed=21)[0], max_new_tokens=4,
                        request_class="batch")]
        assert fe.wait_all(hs, timeout=300)
        snap = fe.metrics_snapshot()
        assert snap["ttft_s_class_interactive"]["count"] == 1
        assert snap["ttft_s_class_batch"]["count"] == 1
        assert snap["tpot_s_class_batch"]["count"] == 3
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_per_role_occupancy_gauges_published():
    fe = ServingFrontend([tiny_engine(), tiny_engine()],
                         disagg_config(["prefill", "decode"]))
    try:
        hs = [fe.submit(p, max_new_tokens=4) for p in prompts(2, seed=22)]
        assert fe.wait_all(hs, timeout=300)
        snap = fe.metrics_snapshot()
        assert "kv_blocks_in_use_role_prefill" in snap
        assert "kv_blocks_in_use_role_decode" in snap
    finally:
        fe.shutdown(drain=False, timeout=5)


def test_disaggregation_config_mounts_on_ds_config():
    from deepspeed_tpu.runtime.config import DeepSpeedTpuConfig

    cfg = DeepSpeedTpuConfig(
        train_micro_batch_size_per_gpu=1,
        serving={"enabled": True,
                 "disaggregation": {"enabled": True,
                                    "roles": ["prefill", "decode"],
                                    "decode_reserve_tokens": 16,
                                    "handoff": {"max_staged": 4}},
                 "classes": {"interactive": {"deadline_ms": 500.0},
                             "batch": {"priority": 2, "shed_rank": 1}}})
    dis = cfg.serving.disaggregation
    assert dis.enabled and dis.roles == ["prefill", "decode"]
    assert dis.decode_reserve_tokens == 16
    assert dis.handoff.max_staged == 4
    assert cfg.serving.classes["interactive"].deadline_ms == 500.0
    assert cfg.serving.classes["batch"].shed_rank == 1
    # defaults: disabled, all-mixed, stock class map
    d2 = DeepSpeedTpuConfig(train_micro_batch_size_per_gpu=1)
    assert d2.serving.disaggregation.enabled is False
    assert d2.serving.disaggregation.role_of(0) == "mixed"
    assert set(d2.serving.classes) == {"interactive", "batch"}


def test_custom_class_map_merges_over_stock_classes():
    """Adding a custom class must not silently delete the stock
    interactive/batch entries the default_class points at."""
    c = ServingConfig(classes={"vip": {"priority": 0, "shed_rank": 0}})
    assert set(c.classes) == {"vip", "interactive", "batch"}
    assert c.classes["batch"].shed_rank == 1
    assert c.classes["vip"].priority == 0
